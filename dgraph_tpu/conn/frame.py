"""Binary multipart framing for the inter-node data plane.

The reference's internal RPC is typed protobuf over gRPC with snappy
compression for bulk payloads (conn/snappy.go; worker/snapshot.go:177
streams raft snapshots, predicate moves stream tablet KVs). Our control
plane speaks length-prefixed JSON (conn/rpc.py) — fine for small
messages, but base64-tagging every key/value byte string inflates bulk
transfers ~1.33x and burns CPU on encode/decode.

This codec keeps JSON for structure and lifts LARGE byte strings out as
raw binary blobs, zlib-compressed when that pays:

    body := 0x01 | u32 json_len | json | blob*
    blob := u32 len | u8 flag | payload
    flag 0: payload = raw bytes (len of them)
    flag 1: payload = bare zlib stream (legacy; decode-only, inflated
            under the absolute cap)
    flag 2: payload = u32 raw_len | zlib stream (the raw_len header
            bounds decompression per blob, and the decoder also caps
            the aggregate inflated size of a frame, so a corrupt or
            hostile frame cannot expand past _MAX_INFLATE total)

Inside the JSON, an extracted blob is {"__blob__": i}; small byte
strings keep the existing {"__b64__": ...} tag (b64 overhead on 50
bytes is noise, and it keeps frames introspectable). A body starting
with '{' (0x7b) is plain JSON — the decoder accepts both, so the two
framings coexist on one socket protocol.

JSON (not pickle) remains deliberate: the wire never executes code.

Version note: flag-2 blobs and __esc__ wrapping require every node to
run this revision or later (older decoders pass both through wrong).
The cluster deploys from one tree and compression is opt-in
(DGRAPH_TPU_WIRE_COMPRESS), so no negotiation layer is carried here;
if rolling upgrades across framing revisions become real, bump MAGIC
and negotiate per-connection in conn/rpc.py's hello exchange.
"""

from __future__ import annotations

import base64
import json
import struct
import zlib
from typing import Any, List, Tuple

from dgraph_tpu.x import config

MAGIC = 0x01
_U32 = struct.Struct(">I")
# Hard cap on a single wire frame (header-declared length). A corrupt or
# hostile 4-byte length prefix must never drive an arbitrarily large
# allocation in _recv_frame readers; matches the reference's 256MB gRPC
# message cap (conn/pool.go grpc.MaxCallRecvMsgSize). Shared by
# conn/rpc.py and raft/tcp.py so both planes enforce the same bound.
MAX_FRAME = int(config.get("MAX_FRAME_BYTES"))
_BLOB_MIN = 256  # bytes values at least this long leave the JSON
_ZLIB_LEVEL = 1
# Compression default OFF: raw blobs already beat the old JSON+b64 path
# 10x on encode+decode CPU and 1.33x on bytes (FRAMING_BENCH.json), and
# zlib-1 (~100MB/s) is SLOWER than LAN/ICI-class links — the reference
# affords always-on compression only because snappy is ~free, which the
# Python stdlib cannot match. Set DGRAPH_TPU_WIRE_COMPRESS=1 for
# DCN-class links where 2.8x fewer bytes wins; blobs are sample-probed
# so incompressible payloads skip the cost either way.
_COMPRESS = bool(config.get("WIRE_COMPRESS"))
_ZLIB_MIN = 1 << 16  # probe/compress only genuinely bulk blobs
_PROBE = 4096


def _worth_compressing(b: bytes) -> bool:
    sample = b[:_PROBE]
    return len(zlib.compress(sample, _ZLIB_LEVEL)) < (len(sample) * 7) // 8


# A user-level dict whose single key collides with a codec sentinel
# ({"__blob__": …}, {"__b64__": …}, {"__esc__": …}) is wrapped in
# {"__esc__": …} on extract and unwrapped on restore, so payload data
# can never be misread as a blob reference.
_SENTINELS = frozenset(("__blob__", "__b64__", "__esc__"))


def _extract(obj: Any, blobs: List[bytes]) -> Any:
    if isinstance(obj, (bytes, bytearray)):
        b = bytes(obj)
        if len(b) >= _BLOB_MIN:
            blobs.append(b)
            return {"__blob__": len(blobs) - 1}
        return {"__b64__": base64.b64encode(b).decode()}
    if isinstance(obj, (list, tuple)):
        return [_extract(x, blobs) for x in obj]
    if isinstance(obj, dict):
        out = {k: _extract(v, blobs) for k, v in obj.items()}
        if len(out) == 1 and next(iter(out)) in _SENTINELS:
            return {"__esc__": out}
        return out
    return obj


def _restore(obj: Any, blobs: List[bytes]) -> Any:
    if isinstance(obj, list):
        return [_restore(x, blobs) for x in obj]
    if isinstance(obj, dict):
        if set(obj.keys()) == {"__esc__"}:
            inner = obj["__esc__"]
            if not isinstance(inner, dict):
                raise FrameError("__esc__ payload must be an object")
            # the escaped dict's own key is literal — only its value is
            # recursed, so a payload {"__blob__": x} survives round-trip
            return {k: _restore(v, blobs) for k, v in inner.items()}
        if set(obj.keys()) == {"__blob__"}:
            i = obj["__blob__"]
            if not isinstance(i, int) or isinstance(i, bool) or not (
                0 <= i < len(blobs)
            ):
                raise FrameError(f"dangling blob ref: {i!r}")
            return blobs[i]
        if set(obj.keys()) == {"__b64__"}:
            v = obj["__b64__"]
            if not isinstance(v, str):
                raise FrameError("__b64__ payload must be a string")
            try:
                return base64.b64decode(v)
            except (ValueError, TypeError) as e:
                raise FrameError(f"bad base64 payload: {e}") from e
        return {k: _restore(v, blobs) for k, v in obj.items()}
    return obj


def pack_body(obj: Any) -> bytes:
    """Serialize to either plain JSON (no big byte strings) or the
    binary multipart body."""
    blobs: List[bytes] = []
    jobj = _extract(obj, blobs)
    jb = json.dumps(jobj).encode()
    if not blobs:
        return jb
    out = [bytes([MAGIC]), _U32.pack(len(jb)), jb]
    for b in blobs:
        if _COMPRESS and len(b) >= _ZLIB_MIN and _worth_compressing(b):
            comp = zlib.compress(b, _ZLIB_LEVEL)
            if len(comp) + 4 < len(b):
                out.append(_U32.pack(len(comp) + 4))
                out.append(b"\x02")
                out.append(_U32.pack(len(b)))
                out.append(comp)
                continue
        out.append(_U32.pack(len(b)))
        out.append(b"\x00")
        out.append(b)
    return b"".join(out)


class FrameError(ValueError):
    """Corrupt or truncated frame body. Subclasses ValueError so the
    transports' existing malformed-input guards catch it."""


# Absolute inflation ceiling: raw_len is sender-declared, so it alone
# can't bound a hostile frame. Matches the reference's 256MB gRPC
# message cap (conn/pool.go grpc.MaxCallRecvMsgSize) — anything bulkier
# is streamed in chunks by the snapshot/move paths, never one frame.
_MAX_INFLATE = 256 << 20


def _check_stream_end(d, raw_len) -> None:
    if d.unconsumed_tail or d.flush():
        raise FrameError(
            f"compressed blob inflates past declared {raw_len} bytes"
        )
    if d.unused_data:
        # bytes after the stream's end marker: junk or a covert channel
        raise FrameError("trailing bytes after compressed stream")
    if not d.eof:
        # stream truncated before its adler32 trailer: the checksum was
        # never verified, so the bytes cannot be trusted
        raise FrameError("compressed blob truncated (checksum unverified)")


def _inflate(raw: bytes, budget: int) -> bytes:
    """Decompress a flag-2 blob payload with its declared raw_len as a
    hard output bound (a hostile 1KB frame could otherwise inflate to
    gigabytes — the length prefix only bounds the compressed size).
    `budget` is the frame's remaining aggregate allowance."""
    if len(raw) < 4:
        raise FrameError("compressed blob too short for raw_len header")
    (raw_len,) = _U32.unpack_from(raw, 0)
    if raw_len > budget:
        raise FrameError(
            f"blob declares {raw_len} bytes, frame budget is {budget}"
        )
    d = zlib.decompressobj()
    # max_length=0 would mean "unbounded" to zlib; a declared-empty blob
    # still gets a 1-byte cap so the length check below can reject it
    out = d.decompress(raw[4:], max(raw_len, 1))
    if len(out) != raw_len:
        raise FrameError(
            f"compressed blob declared {raw_len} bytes, got {len(out)}"
        )
    _check_stream_end(d, raw_len)
    return out


def _inflate_legacy(raw: bytes, budget: int) -> bytes:
    """Flag-1 (bare zlib, no raw_len header) decode for frames from
    pre-raw_len senders; bounded by the frame's remaining budget."""
    d = zlib.decompressobj()
    out = d.decompress(raw, budget + 1)
    if len(out) > budget:
        raise FrameError(
            f"legacy compressed blob exceeds frame budget {budget}"
        )
    _check_stream_end(d, len(out))
    return out


def unpack_body(body: bytes) -> Any:
    """Inverse of pack_body; accepts plain-JSON bodies too. Raises
    FrameError (a ValueError) on any corruption — truncated headers,
    overrunning blob lengths, bad zlib streams, dangling blob refs."""
    try:
        if not body or body[0] != MAGIC:
            return _restore(json.loads(body), [])
        (jlen,) = _U32.unpack_from(body, 1)
        pos = 5 + jlen
        jobj = json.loads(body[5:pos])
        blobs: List[bytes] = []
        end = len(body)
        # aggregate inflation budget: many small blobs must not add up
        # past the cap any more than one big one may
        budget = _MAX_INFLATE
        while pos < end:
            (n,) = _U32.unpack_from(body, pos)
            flag = body[pos + 5 - 1]
            pos += 5
            if pos + n > end:
                raise FrameError(
                    f"blob overruns frame: need {n} bytes at {pos}, "
                    f"have {end - pos}"
                )
            raw = body[pos : pos + n]
            pos += n
            if flag == 2:
                b = _inflate(raw, budget)
            elif flag == 1:
                b = _inflate_legacy(raw, budget)
            elif flag == 0:
                b = raw
            else:
                raise FrameError(f"unknown blob flag {flag}")
            budget -= len(b)
            if budget < 0:
                # flag-0 raw blobs spend the same budget: a frame's
                # total decoded payload may never exceed the cap, and a
                # negative budget must not reach zlib's max_length
                raise FrameError(
                    f"frame payload exceeds {_MAX_INFLATE}-byte cap"
                )
            blobs.append(b)
        return _restore(jobj, blobs)
    except FrameError:
        raise
    except (
        struct.error,
        zlib.error,
        IndexError,
        TypeError,
        json.JSONDecodeError,
    ) as e:
        raise FrameError(f"corrupt frame: {type(e).__name__}: {e}") from e

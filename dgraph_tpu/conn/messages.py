"""Typed control-plane messages over the binary frame layer.

The reference's internal planes speak typed protobuf
(/root/reference/protos/pb.proto:559-604 — services Raft/Zero/Worker;
badgerpb4.KV for streamed records). This module is the analog: a
protobuf-WIRE-FORMAT codec (varint tags, length-delimited fields — so
the bytes are inspectable with any proto tool) plus one schema for
every message the Alpha/Zero/Raft processes exchange. JSON stays only
where the reference also nests opaque app bytes (raftpb.Entry.Data,
ZeroProposal internals).

Encoding rules (proto3 subset):
  tag   = (field_num << 3) | wire_type
  wire 0 = varint  (uint/bool)
  wire 2 = length-delimited (bytes/str/nested message/repeated message)
Unknown fields are skipped on decode (forward compatibility).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# wire primitives
# ---------------------------------------------------------------------------


def _put_varint(out: List[bytes], v: int):
    if v < 0:
        raise ValueError(f"varint cannot encode negative value {v}")
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(bytes([b | 0x80]))
        else:
            out.append(bytes([b]))
            return


def _get_varint(data: bytes, pos: int) -> Tuple[int, int]:
    shift = 0
    val = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated varint")
        b = data[pos]
        pos += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


class Message:
    """Base: subclasses declare FIELDS = {name: (num, spec)} where spec
    is 'uint' | 'bool' | 'bytes' | 'str' | ('msg', cls) |
    ('rep', inner_spec)."""

    FIELDS: Dict[str, Tuple[int, Any]] = {}

    def __init__(self, **kw):
        for name, (_, spec) in self.FIELDS.items():
            v = kw.pop(name, None)
            if v is None:
                v = self._zero(spec)
            setattr(self, name, v)
        if kw:
            raise TypeError(f"unknown fields {sorted(kw)}")

    @staticmethod
    def _zero(spec):
        if spec == "uint":
            return 0
        if spec == "bool":
            return False
        if spec == "bytes":
            return b""
        if spec == "str":
            return ""
        if isinstance(spec, tuple) and spec[0] == "rep":
            return []
        return None  # nested message

    def __eq__(self, other):
        return type(self) is type(other) and all(
            getattr(self, n) == getattr(other, n) for n in self.FIELDS
        )

    def __repr__(self):
        inner = ", ".join(
            f"{n}={getattr(self, n)!r}" for n in self.FIELDS
        )
        return f"{type(self).__name__}({inner})"

    # -- encode ---------------------------------------------------------

    def encode(self) -> bytes:
        out: List[bytes] = []
        for name, (num, spec) in self.FIELDS.items():
            v = getattr(self, name)
            self._enc_field(out, num, spec, v)
        return b"".join(out)

    @classmethod
    def _enc_field(cls, out, num, spec, v):
        if isinstance(spec, tuple) and spec[0] == "rep":
            for item in v or []:
                cls._enc_field(out, num, spec[1], item)
            return
        if spec == "uint":
            if v:
                _put_varint(out, (num << 3) | 0)
                _put_varint(out, int(v))
            return
        if spec == "bool":
            if v:
                _put_varint(out, (num << 3) | 0)
                _put_varint(out, 1)
            return
        if spec in ("bytes", "str"):
            b = v.encode("utf-8") if spec == "str" else bytes(v)
            if b:
                _put_varint(out, (num << 3) | 2)
                _put_varint(out, len(b))
                out.append(b)
            return
        if isinstance(spec, tuple) and spec[0] == "msg":
            if v is not None:
                b = v.encode()
                _put_varint(out, (num << 3) | 2)
                _put_varint(out, len(b))
                out.append(b)
            return
        raise TypeError(f"bad field spec {spec!r}")

    # -- decode ---------------------------------------------------------

    @classmethod
    def decode(cls, data: bytes) -> "Message":
        m = cls()
        by_num = {num: (name, spec) for name, (num, spec) in cls.FIELDS.items()}
        pos = 0
        n = len(data)
        while pos < n:
            tag, pos = _get_varint(data, pos)
            num, wt = tag >> 3, tag & 7
            if wt == 0:
                val, pos = _get_varint(data, pos)
                payload: Any = val
            elif wt == 2:
                ln, pos = _get_varint(data, pos)
                if pos + ln > n:
                    raise ValueError("truncated field")
                payload = data[pos : pos + ln]
                pos += ln
            else:
                raise ValueError(f"unsupported wire type {wt}")
            got = by_num.get(num)
            if got is None:
                continue  # unknown field: skip (forward compat)
            name, spec = got
            rep = isinstance(spec, tuple) and spec[0] == "rep"
            inner = spec[1] if rep else spec
            if inner == "uint":
                val2: Any = int(payload)
            elif inner == "bool":
                val2 = bool(payload)
            elif inner == "bytes":
                val2 = bytes(payload)
            elif inner == "str":
                val2 = bytes(payload).decode("utf-8")
            elif isinstance(inner, tuple) and inner[0] == "msg":
                val2 = inner[1].decode(bytes(payload))
            else:
                raise TypeError(f"bad field spec {spec!r}")
            if rep:
                getattr(m, name).append(val2)
            else:
                setattr(m, name, val2)
        return m


# ---------------------------------------------------------------------------
# control-plane schemas (pb.proto:559-604 analogs)
# ---------------------------------------------------------------------------


class KV(Message):
    """badgerpb4.KV analog: one versioned record."""

    FIELDS = {"key": (1, "bytes"), "ts": (2, "uint"), "value": (3, "bytes")}


class KVList(Message):
    """pb.KVS analog: a streamed record batch. `more` marks a paged
    iterate_versions response truncated at a key boundary by the
    request's max_bytes cap — the caller resumes with after=<last key>
    (the tablet-move copy stream; old decoders skip the field)."""

    FIELDS = {"kv": (1, ("rep", ("msg", KV))), "more": (2, "bool")}


class HealthInfo(Message):
    """pb.HealthInfo analog (service Raft.Heartbeat)."""

    FIELDS = {
        "ok": (1, "bool"),
        "node": (2, "uint"),
        "group": (3, "uint"),
        "is_leader": (4, "bool"),
        "term": (5, "uint"),
        "applied": (6, "uint"),
    }


class GetRequest(Message):
    FIELDS = {"key": (1, "bytes"), "ts": (2, "uint")}


class GetResponse(Message):
    FIELDS = {"found": (1, "bool"), "ts": (2, "uint"), "value": (3, "bytes")}


class IterateRequest(Message):
    """Prefix scan. The optional fields page and filter a versions scan
    so one response frame stays bounded (tablet moves stream tablets
    far larger than DGRAPH_TPU_MAX_FRAME_BYTES in chunks):
      since     only versions with ts > since (delta-phase catch-up)
      after     resume strictly after this key (page cursor)
      max_bytes stop at the first key boundary past this many record
                bytes and set KVList.more (0 = unpaged)."""

    FIELDS = {
        "prefix": (1, "bytes"),
        "ts": (2, "uint"),
        "since": (3, "uint"),
        "after": (4, "bytes"),
        "max_bytes": (5, "uint"),
    }


class Proposal(Message):
    """Raft proposal envelope; data is the app-level op (opaque bytes,
    like raftpb.Entry.Data)."""

    FIELDS = {"data": (1, "bytes")}


class ProposalResponse(Message):
    FIELDS = {
        "ok": (1, "bool"),
        "error": (2, "str"),
        "leader_hint": (3, "uint"),
        "index": (4, "uint"),
    }


class Ack(Message):
    """api.Payload/Status analog for fire-and-forget admin ops."""

    FIELDS = {"ok": (1, "bool")}


class ZeroState(Message):
    """MembershipState analog; the state snapshot rides as structured
    JSON (it is a full coordinator dump, not a hot-path record)."""

    FIELDS = {"state_json": (1, "bytes")}


class ZeroCommitReq(Message):
    """One member of a batched commit exchange: a txn's start ts plus
    its conflict-key fingerprints (pb.TxnContext analog — the keys are
    already 64-bit fingerprints on this plane, varint-encoded here)."""

    FIELDS = {"start_ts": (1, "uint"), "cks": (2, ("rep", "uint"))}


class ZeroCommitBatch(Message):
    """The group-commit oracle exchange: N (start_ts, conflict_keys)
    sets decided in ONE Zero round trip, verdicts returned per txn (an
    aborted member never fails its batchmates). Rides as a typed
    nested field on ZeroExec — u64 fingerprint lists stay varints
    instead of JSON numerals, and the zero-process arg normalizer
    never sees (and can't mangle) the nested list shape."""

    FIELDS = {"txns": (1, ("rep", ("msg", ZeroCommitReq)))}


class ZeroExec(Message):
    """ZeroProposal analog: one Zero state-machine op. args is the
    op-specific body (structured JSON — Zero ops are heterogeneous,
    like pb.ZeroProposal's oneof); `commit_batch` is the typed body of
    the batched commit op (decoders that predate it skip the field)."""

    FIELDS = {
        "op": (1, "str"),
        "args_json": (2, "bytes"),
        "commit_batch": (3, ("msg", ZeroCommitBatch)),
    }


class RaftEnvelope(Message):
    """raftpb.Message analog for the raft TCP plane; payload nests the
    kind-specific body as an opaque framed blob (entries carry app
    proposal data, like raftpb.Entry.Data — the frame codec keeps bulk
    snapshot bytes raw instead of base64). `trace` carries the ambient
    W3C traceparent of the sender (empty for untraced tick traffic) so
    a traced proposal's replication hop stays attributable; decoders
    that predate the field skip it (forward compat)."""

    FIELDS = {
        "kind": (1, "str"),
        "frm": (2, "uint"),
        "to": (3, "uint"),
        "term": (4, "uint"),
        "payload": (5, "bytes"),
        "trace": (6, "str"),
    }


# registry for the frame layer: name -> class
REGISTRY: Dict[str, type] = {
    c.__name__: c
    for c in (
        KV, KVList, HealthInfo, GetRequest, GetResponse,
        IterateRequest, Proposal, ProposalResponse, Ack, ZeroState,
        ZeroExec, ZeroCommitReq, ZeroCommitBatch, RaftEnvelope,
    )
}


def to_wire(msg: Message) -> dict:
    """Envelope a typed message for the JSON+blob frame layer."""
    return {"__typed__": type(msg).__name__, "__pb__": msg.encode()}


def from_wire(obj) -> Optional[Message]:
    if isinstance(obj, dict) and "__typed__" in obj:
        cls = REGISTRY.get(obj["__typed__"])
        if cls is None:
            raise ValueError(f"unknown typed message {obj['__typed__']}")
        return cls.decode(obj["__pb__"])
    return None

"""Request/response RPC over TCP: the conn/pool.go equivalent.

The reference maintains one gRPC ClientConn per peer inside a Pool with
health checks (conn/pool.go:52 Pool, :233 MonitorHealth, :292
IsHealthy). This is the socket equivalent for dgraph-tpu's cross-process
cluster: length-prefixed frames (conn/frame.py codec), persistent
pooled connections with reconnect, periodic heartbeat pings, and
per-peer health state.

Framing: 4-byte big-endian length + body, where body is either plain
JSON or conn/frame.py's binary multipart (JSON header + raw blobs,
zlib-compressed — the snappy-stream analog, ref conn/snappy.go): bulk
payloads (raft snapshots, predicate-move streams, pack transfer) ride
as raw bytes instead of base64.
  request:  {"id": n, "m": method, "a": args}
  response: {"id": n, "r": result} | {"id": n, "e": error_string}

JSON (not pickle) on purpose: the wire should never execute code.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from dgraph_tpu.conn.frame import pack_body, unpack_body

_LEN = struct.Struct(">I")


class RpcError(RuntimeError):
    pass


def _send_frame(sock: socket.socket, obj: dict):
    body = pack_body(obj)
    sock.sendall(_LEN.pack(len(body)) + body)


def _recv_frame(rfile) -> Optional[dict]:
    hdr = rfile.read(_LEN.size)
    if len(hdr) < _LEN.size:
        return None
    (n,) = _LEN.unpack(hdr)
    body = rfile.read(n)
    if len(body) < n:
        return None
    return unpack_body(body)


class RpcServer:
    """Serves registered handlers; one thread per connection."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.handlers: Dict[str, Callable[[dict], Any]] = {}
        self.register("ping", lambda a: {"pong": True, "t": time.time()})
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                while True:
                    try:
                        req = _recv_frame(self.rfile)
                    except (OSError, ValueError, struct.error):
                        return
                    if req is None:
                        return
                    rid = req.get("id")
                    fn = outer.handlers.get(req.get("m"))
                    try:
                        if fn is None:
                            raise RpcError(f"no such method {req.get('m')!r}")
                        from dgraph_tpu.conn.messages import (
                            Message,
                            from_wire,
                            to_wire,
                        )

                        args = req.get("a") or {}
                        typed = from_wire(args)
                        result = fn(typed if typed is not None else args)
                        if isinstance(result, Message):
                            result = to_wire(result)
                        resp = {"id": rid, "r": result}
                    except Exception as e:  # surface to caller, keep serving
                        resp = {"id": rid, "e": f"{type(e).__name__}: {e}"}
                    try:
                        _send_frame(self.connection, resp)
                    except OSError:
                        return

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = _Server((host, port), Handler)
        self.addr: Tuple[str, int] = self._srv.server_address[:2]
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True
        )

    def register(self, method: str, fn: Callable[[dict], Any]):
        self.handlers[method] = fn

    def start(self):
        self._thread.start()
        return self

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()


class RpcClient:
    """One persistent connection to a peer, with reconnect."""

    def __init__(self, addr: Tuple[str, int], timeout: float = 5.0):
        self.addr = tuple(addr)
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._rfile = None
        self._lock = threading.Lock()
        self._next_id = 0

    def _connect(self):
        s = socket.create_connection(self.addr, timeout=self.timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.settimeout(self.timeout)
        self._sock = s
        self._rfile = s.makefile("rb")

    def call(self, method: str, args: Optional[dict] = None, timeout=None):
        from dgraph_tpu.conn.messages import Message, from_wire, to_wire

        if isinstance(args, Message):
            args = to_wire(args)  # typed control-plane message
        with self._lock:
            deadline = time.time() + (timeout or self.timeout)
            last_err: Optional[Exception] = None
            while time.time() < deadline:
                try:
                    if self._sock is None:
                        self._connect()
                    self._next_id += 1
                    rid = self._next_id
                    if timeout:
                        self._sock.settimeout(timeout)
                    _send_frame(
                        self._sock,
                        {"id": rid, "m": method, "a": args or {}},
                    )
                    resp = _recv_frame(self._rfile)
                    if resp is None:
                        raise OSError("connection closed")
                    if resp.get("e"):
                        raise RpcError(resp["e"])
                    r = resp.get("r")
                    typed = from_wire(r)
                    return typed if typed is not None else r
                except (OSError, socket.timeout) as e:
                    last_err = e
                    self.close_conn()
                    time.sleep(0.05)
            raise RpcError(f"rpc {method} to {self.addr} failed: {last_err}")

    def close_conn(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        self._rfile = None


class RpcPool:
    """Pool of peer clients with heartbeat health (conn/pool.go:233).

    `healthy(addr)` is False once a peer misses `max_misses` consecutive
    pings; a successful ping (or call) restores it. Dead peers' sockets
    are pruned so reconnects start fresh."""

    def __init__(
        self,
        heartbeat_s: float = 1.0,
        timeout: float = 5.0,
        max_misses: int = 3,
    ):
        self.timeout = timeout
        self.heartbeat_s = heartbeat_s
        self.max_misses = max_misses
        self._clients: Dict[Tuple[str, int], RpcClient] = {}
        self._misses: Dict[Tuple[str, int], int] = {}
        self._last_ok: Dict[Tuple[str, int], float] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None

    def get(self, addr) -> RpcClient:
        addr = tuple(addr)
        with self._lock:
            c = self._clients.get(addr)
            if c is None:
                c = RpcClient(addr, timeout=self.timeout)
                self._clients[addr] = c
                self._misses.setdefault(addr, 0)
            return c

    def call(self, addr, method, args=None, timeout=None):
        c = self.get(addr)
        try:
            out = c.call(method, args, timeout=timeout)
            self._mark(addr, ok=True)
            return out
        except RpcError:
            self._mark(addr, ok=False)
            raise

    def _mark(self, addr, ok: bool):
        addr = tuple(addr)
        with self._lock:
            if ok:
                self._misses[addr] = 0
                self._last_ok[addr] = time.time()
            else:
                self._misses[addr] = self._misses.get(addr, 0) + 1
                if self._misses[addr] >= self.max_misses:
                    c = self._clients.get(addr)
                    if c is not None:
                        c.close_conn()  # prune the dead socket

    def healthy(self, addr) -> bool:
        return self._misses.get(tuple(addr), 0) < self.max_misses

    def start_heartbeats(self):
        """Background pinger marking peer health (MonitorHealth analog)."""
        if self._hb_thread is not None:
            return self
        self._hb_thread = threading.Thread(target=self._hb_loop, daemon=True)
        self._hb_thread.start()
        return self

    def _hb_loop(self):
        while not self._stop.wait(self.heartbeat_s):
            with self._lock:
                addrs = list(self._clients)
            for addr in addrs:
                try:
                    self.get(addr).call("ping", timeout=self.heartbeat_s)
                    self._mark(addr, ok=True)
                except RpcError:
                    self._mark(addr, ok=False)

    def close(self):
        self._stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2)
        with self._lock:
            for c in self._clients.values():
                c.close_conn()
            self._clients.clear()

"""Request/response RPC over TCP: the conn/pool.go equivalent.

The reference maintains one gRPC ClientConn per peer inside a Pool with
health checks (conn/pool.go:52 Pool, :233 MonitorHealth, :292
IsHealthy). This is the socket equivalent for dgraph-tpu's cross-process
cluster: length-prefixed frames (conn/frame.py codec), persistent
pooled connections with reconnect, periodic heartbeat pings, and
per-peer health state with a circuit breaker (open after `max_misses`
consecutive failures; half-open probes ride the heartbeat, so a dead
peer costs a fail-fast instead of a full timeout per call).

Framing: 4-byte big-endian length + body (bounded by frame.MAX_FRAME),
where body is either plain JSON or conn/frame.py's binary multipart:
  request:  {"id": n, "m": method, "a": args[, "c": client_id, "q": seq]}
  response: {"id": n, "r": result} | {"id": n, "e": error_string}

`c`/`q` are the idempotency key: a connection-independent client id and
a per-logical-call sequence number, attached when the caller marks a
call `idem=True` (proposals, zero.exec, lease grants — anything whose
reconnect-and-resend must not double-apply). The server keeps a small
LRU of completed (client, seq) -> response, plus in-flight tracking so
a retransmit racing the original waits for it rather than re-running.

Failure handling is uniform (conn/retry.py): every call runs under a
Deadline (explicit, ambient via deadline_scope, or derived from the
timeout) with exponential-backoff + full-jitter retries, and the
transports consult conn/faults.py at the send/recv/resp points so chaos
schedules can deterministically drop/delay/duplicate/disconnect.

JSON (not pickle) on purpose: the wire should never execute code.
"""

from __future__ import annotations

import os
import socket
import socketserver
import struct
import threading
import time
import uuid
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

from dgraph_tpu.conn import faults
from dgraph_tpu.conn.frame import MAX_FRAME, FrameError, pack_body, unpack_body
from dgraph_tpu.conn.retry import Deadline, RetryPolicy
from dgraph_tpu.utils.observe import (
    METRICS,
    TRACER,
    current_profile,
    parse_traceparent,
)

_LEN = struct.Struct(">I")


class RpcError(RuntimeError):
    pass


class PeerDownError(RpcError):
    """Fail-fast refusal: the peer's circuit is open (it missed
    `max_misses` consecutive probes). Heartbeat pings keep probing and
    close the circuit when the peer answers again."""


class OversizeFrameError(RpcError):
    """The frame we are about to SEND exceeds MAX_FRAME. Not retryable —
    the receiver would reject it every time; fail the call immediately
    with a clear error instead of resending until the deadline."""


def _send_frame(sock: socket.socket, obj: dict):
    body = pack_body(obj)
    if len(body) > MAX_FRAME:
        METRICS.inc("frame_oversize_total")
        raise OversizeFrameError(
            f"outgoing frame of {len(body)} bytes exceeds the "
            f"{MAX_FRAME}-byte cap (DGRAPH_TPU_MAX_FRAME_BYTES); bulk "
            f"payloads this large must stream in chunks"
        )
    sock.sendall(_LEN.pack(len(body)) + body)


def _respond(conn: socket.socket, resp: dict) -> bool:
    """Send a response frame; an oversized response degrades to a small
    error reply so the connection (and handler thread) survive."""
    try:
        _send_frame(conn, resp)
        return True
    except OversizeFrameError as e:
        try:
            _send_frame(conn, {"id": resp.get("id"), "e": f"RpcError: {e}"})
            return True
        except OSError:
            return False
    except OSError:
        return False


def _recv_frame(rfile) -> Optional[dict]:
    hdr = rfile.read(_LEN.size)
    if len(hdr) < _LEN.size:
        return None
    (n,) = _LEN.unpack(hdr)
    if n > MAX_FRAME:
        # a corrupt length header must not drive an n-byte allocation;
        # raising (a ValueError) makes both sides drop the connection
        METRICS.inc("frame_oversize_total")
        raise FrameError(f"frame length {n} exceeds {MAX_FRAME}-byte cap")
    body = rfile.read(n)
    if len(body) < n:
        return None
    return unpack_body(body)


class RpcServer:
    """Serves registered handlers; one thread per connection.

    Requests carrying an idempotency key (`c`, `q`) are deduplicated
    against a bounded LRU of completed responses, so a client resending
    after a lost ack cannot double-apply a non-idempotent handler."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 idem_cache: int = 1024, instance: str = ""):
        # per-process label stamped on rpc_server spans and piggybacked
        # profile fragments (alpha/zero processes set "alpha-<id>" etc.)
        self.instance = instance or f"pid{os.getpid()}"
        self.handlers: Dict[str, Callable[[dict], Any]] = {}
        self.register("ping", lambda a: {"pong": True, "t": time.time()})
        self._idem_cap = idem_cache
        self._idem: "OrderedDict[Tuple[str, int], dict]" = OrderedDict()
        self._inflight: Dict[Tuple[str, int], threading.Event] = {}
        self._idem_lock = threading.Lock()
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                peer = "%s:%s" % tuple(self.client_address[:2])
                while True:
                    try:
                        req = _recv_frame(self.rfile)
                    except (OSError, ValueError, struct.error):
                        return  # oversized/corrupt frame: drop the conn
                    if req is None:
                        return
                    method = req.get("m") or ""
                    act = _fault("recv", peer, method)
                    if act is not None:
                        if act.action == "drop":
                            continue  # request lost before handling
                        if act.action in ("disconnect", "partition"):
                            return
                        if act.action == "delay":
                            time.sleep(act.delay_s)
                    resp = outer._dispatch(req)
                    act = _fault("resp", peer, method)
                    if act is not None:
                        if act.action == "drop":
                            continue  # applied, but the ack is lost
                        if act.action in ("disconnect", "partition"):
                            return
                        if act.action == "delay":
                            time.sleep(act.delay_s)
                        elif act.action == "dup":
                            if not _respond(self.connection, resp):
                                return
                    if not _respond(self.connection, resp):
                        return

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = _Server((host, port), Handler)
        self.addr: Tuple[str, int] = self._srv.server_address[:2]
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True
        )

    # -- request execution ---------------------------------------------------

    def _execute(self, req: dict) -> dict:
        """Run the handler. A request carrying a `tp` traceparent joins
        the caller's trace: its context is attached around the handler
        so server-side spans parent correctly, one rpc_server span
        covers the execution, and a profile fragment (instance, method,
        ms) rides back on the response (`p`) for the client's
        QueryProfile — the reference's per-query server-side latency
        attribution, made cross-process."""
        rid = req.get("id")
        method = req.get("m")
        fn = self.handlers.get(method)
        ctx = parse_traceparent(req["tp"]) if req.get("tp") else None
        token = TRACER.attach(ctx) if ctx is not None else None
        t0 = time.perf_counter()
        try:
            if fn is None:
                raise RpcError(f"no such method {method!r}")
            from dgraph_tpu.conn.messages import Message, from_wire, to_wire

            args = req.get("a") or {}
            typed = from_wire(args)
            if ctx is not None:
                METRICS.inc("rpc_server_requests_total")
                with TRACER.span(
                    "rpc_server", method=method, instance=self.instance
                ):
                    result = fn(typed if typed is not None else args)
            else:
                result = fn(typed if typed is not None else args)
            if isinstance(result, Message):
                result = to_wire(result)
            resp = {"id": rid, "r": result}
        except Exception as e:  # surface to caller, keep serving
            resp = {"id": rid, "e": f"{type(e).__name__}: {e}"}
        finally:
            if token is not None:
                TRACER.detach(token)
        if ctx is not None:
            resp["p"] = {
                "i": self.instance,
                "m": method,
                "ms": round((time.perf_counter() - t0) * 1e3, 3),
            }
        return resp

    def _dispatch(self, req: dict) -> dict:
        """Execute with idempotency-key dedup: a completed (client, seq)
        returns its cached response; a retransmit racing the original
        waits for it instead of re-running the handler."""
        cid, seq = req.get("c"), req.get("q")
        if cid is None or seq is None:
            return self._execute(req)
        rid = req.get("id")
        try:
            key = (str(cid), int(seq))
        except (TypeError, ValueError):
            # a malformed key must not kill the connection (and every
            # pipelined request on it) — answer the one bad request
            return {"id": rid, "e": "RpcError: malformed idempotency key"}
        owner = False
        with self._idem_lock:
            hit = self._idem.get(key)
            if hit is not None:
                self._idem.move_to_end(key)
                METRICS.inc("idem_hits_total")
                return dict(hit, id=rid)
            ev = self._inflight.get(key)
            if ev is None:
                ev = self._inflight[key] = threading.Event()
                owner = True
        if not owner:
            METRICS.inc("idem_inflight_waits_total")
            ev.wait(timeout=30.0)
            with self._idem_lock:
                hit = self._idem.get(key)
            if hit is not None:
                METRICS.inc("idem_hits_total")
                return dict(hit, id=rid)
            return {"id": rid, "e": "RpcError: duplicate still in flight"}
        resp = None
        try:
            resp = self._execute(req)  # never raises (errors become "e")
            return resp
        finally:
            with self._idem_lock:
                if resp is not None:
                    self._idem[key] = {
                        k: v for k, v in resp.items() if k != "id"
                    }
                    while len(self._idem) > self._idem_cap:
                        self._idem.popitem(last=False)
                self._inflight.pop(key, None)
            ev.set()

    # -- lifecycle -----------------------------------------------------------

    def register(self, method: str, fn: Callable[[dict], Any]):
        self.handlers[method] = fn

    def start(self):
        self._thread.start()
        return self

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()


def _fault(point: str, peer, method: str = ""):
    plan = faults.active()
    if plan is None:
        return None
    return plan.decide(point, peer, method)


class RpcClient:
    """One persistent connection to a peer, with reconnect.

    Reconnect-and-resend is safe for `idem=True` calls: the logical
    call's (client_id, seq) stays constant across attempts, so the
    server's dedup LRU answers retransmits from cache."""

    def __init__(self, addr: Tuple[str, int], timeout: float = 5.0,
                 retry: Optional[RetryPolicy] = None):
        self.addr = tuple(addr)
        self.timeout = timeout
        self.retry = retry or RetryPolicy(base=0.02, cap=1.0)
        self.client_id = uuid.uuid4().hex[:16]
        self._seq = 0
        self._sock: Optional[socket.socket] = None
        self._rfile = None
        self._lock = threading.Lock()
        self._next_id = 0

    def _connect(self, timeout: Optional[float] = None):
        s = socket.create_connection(
            self.addr, timeout=timeout or self.timeout
        )
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.settimeout(self.timeout)
        self._sock = s
        self._rfile = s.makefile("rb")

    def call(self, method: str, args: Optional[dict] = None, timeout=None,
             idem: bool = False, deadline: Optional[Deadline] = None):
        from dgraph_tpu.conn.messages import Message, from_wire, to_wire

        if isinstance(args, Message):
            args = to_wire(args)  # typed control-plane message
        per_attempt = timeout or self.timeout
        # propagate the ambient trace context (W3C traceparent) — stable
        # across every reconnect/resend attempt of this logical call
        tp = TRACER.current_traceparent()
        with self._lock:
            dl = deadline or Deadline.after(per_attempt)
            self._seq += 1
            seq = self._seq  # stable across every attempt of THIS call
            last_err: Optional[Exception] = None
            attempt = 0
            while dl.remaining() > 0:
                try:
                    act = _fault("send", self.addr, method)
                    if act is not None:
                        if act.action == "delay":
                            time.sleep(act.delay_s)
                        elif act.action == "drop":
                            # request lost in transit: we'd wait out the
                            # attempt timeout hearing nothing
                            raise socket.timeout("fault-injected drop")
                        elif act.action == "disconnect":
                            raise OSError("fault-injected disconnect")
                        elif act.action == "partition":
                            raise ConnectionRefusedError(
                                "fault-injected partition"
                            )
                    if self._sock is None:
                        self._connect(timeout=dl.clamp(per_attempt))
                    self._next_id += 1
                    rid = self._next_id
                    # per-attempt timeout, clamped to the deadline; the
                    # client DEFAULT is restored after the reply so one
                    # long-deadline call can't slow later failure
                    # detection (the old settimeout leak)
                    self._sock.settimeout(dl.clamp(per_attempt))
                    req = {"id": rid, "m": method, "a": args or {}}
                    if tp:
                        req["tp"] = tp
                    if idem:
                        req["c"] = self.client_id
                        req["q"] = seq
                    _send_frame(self._sock, req)
                    if act is not None and act.action == "dup":
                        _send_frame(self._sock, req)  # duplicate delivery
                    while True:
                        resp = _recv_frame(self._rfile)
                        if resp is None:
                            raise OSError("connection closed")
                        if resp.get("id") == rid:
                            break
                        # stale reply (e.g. the extra response to a
                        # duplicated request): skip to ours
                        METRICS.inc("rpc_stale_responses_total")
                    self._sock.settimeout(self.timeout)
                    frag = resp.get("p")
                    if frag:
                        prof = current_profile()
                        if prof is not None:
                            prof.record_rpc_fragment(frag)
                    if resp.get("e"):
                        raise RpcError(resp["e"])
                    r = resp.get("r")
                    typed = from_wire(r)
                    return typed if typed is not None else r
                except ConnectionRefusedError as e:
                    # a refusal is definitive — the peer is down or
                    # partitioned; fail fast and let the caller pick
                    # another replica instead of burning the deadline
                    self.close_conn()
                    METRICS.inc("rpc_refused_total")
                    raise RpcError(
                        f"rpc {method} to {self.addr} refused: {e}"
                    ) from e
                except (OSError, socket.timeout, ValueError) as e:
                    last_err = e
                    self.close_conn()
                    attempt += 1
                    METRICS.inc("rpc_retries_total")
                    if self.retry.exhausted(attempt):
                        break
                    self.retry.sleep(attempt, dl)
            METRICS.inc("rpc_giveups_total")
            raise RpcError(
                f"rpc {method} to {self.addr} failed after {attempt} "
                f"attempts: {last_err}"
            )

    def close_conn(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        self._rfile = None


class RpcPool:
    """Pool of peer clients with heartbeat health (conn/pool.go:233).

    `healthy(addr)` is False once a peer misses `max_misses` consecutive
    pings; a successful ping (or call) restores it. While a peer's
    circuit is open, `call` fails fast with PeerDownError instead of
    paying connect/timeout cost — except for half-open probes: the
    background heartbeat keeps pinging (the primary prober), and pools
    without heartbeats let one trial call through per heartbeat window."""

    def __init__(
        self,
        heartbeat_s: float = 1.0,
        timeout: float = 5.0,
        max_misses: int = 3,
    ):
        self.timeout = timeout
        self.heartbeat_s = heartbeat_s
        self.max_misses = max_misses
        self._clients: Dict[Tuple[str, int], RpcClient] = {}
        self._misses: Dict[Tuple[str, int], int] = {}
        self._last_ok: Dict[Tuple[str, int], float] = {}
        self._last_probe: Dict[Tuple[str, int], float] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None

    def get(self, addr) -> RpcClient:
        addr = tuple(addr)
        with self._lock:
            c = self._clients.get(addr)
            if c is None:
                c = RpcClient(addr, timeout=self.timeout)
                self._clients[addr] = c
                self._misses.setdefault(addr, 0)
            return c

    def call(self, addr, method, args=None, timeout=None,
             idem: bool = False, deadline: Optional[Deadline] = None):
        addr = tuple(addr)
        c = self.get(addr)
        if self._failfast(addr):
            METRICS.inc("circuit_failfast_total")
            raise PeerDownError(f"peer {addr} down (circuit open)")
        try:
            out = c.call(method, args, timeout=timeout, idem=idem,
                         deadline=deadline)
            self._mark(addr, ok=True)
            return out
        except RpcError:
            self._mark(addr, ok=False)
            raise

    def _failfast(self, addr) -> bool:
        with self._lock:
            if self._misses.get(addr, 0) < self.max_misses:
                return False
            now = time.time()
            # half-open: without a heartbeat thread, admit one trial
            # call per heartbeat window as the probe
            if now - self._last_probe.get(addr, 0.0) >= self.heartbeat_s:
                self._last_probe[addr] = now
                METRICS.inc("circuit_halfopen_probes_total")
                return False
            return True

    def _mark(self, addr, ok: bool):
        addr = tuple(addr)
        with self._lock:
            was_open = self._misses.get(addr, 0) >= self.max_misses
            if ok:
                self._misses[addr] = 0
                self._last_ok[addr] = time.time()
                if was_open:
                    METRICS.inc("circuit_close_total")
            else:
                self._misses[addr] = self._misses.get(addr, 0) + 1
                if not was_open and self._misses[addr] >= self.max_misses:
                    METRICS.inc("circuit_open_total")
                    # a freshly-opened circuit waits a full heartbeat
                    # window before its first half-open probe
                    self._last_probe[addr] = time.time()
                if self._misses[addr] >= self.max_misses:
                    c = self._clients.get(addr)
                    if c is not None:
                        c.close_conn()  # prune the dead socket

    def healthy(self, addr) -> bool:
        return self._misses.get(tuple(addr), 0) < self.max_misses

    def start_heartbeats(self):
        """Background pinger marking peer health (MonitorHealth analog);
        doubles as the circuit breaker's half-open prober."""
        if self._hb_thread is not None:
            return self
        self._hb_thread = threading.Thread(target=self._hb_loop, daemon=True)
        self._hb_thread.start()
        return self

    def _hb_loop(self):
        while not self._stop.wait(self.heartbeat_s):
            with self._lock:
                addrs = list(self._clients)
            for addr in addrs:
                try:
                    # direct client call: probes bypass the breaker
                    self.get(addr).call("ping", timeout=self.heartbeat_s)
                    self._mark(addr, ok=True)
                except RpcError:
                    self._mark(addr, ok=False)

    def close(self):
        self._stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2)
        with self._lock:
            for c in self._clients.values():
                c.close_conn()
            self._clients.clear()

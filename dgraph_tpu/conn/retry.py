"""Unified retry policy + deadline propagation for cluster RPCs.

Before this module every call site hand-rolled failure handling: fixed
`time.sleep(0.05)` loops in conn/rpc.py, zero/remote.py and
worker/remote.py, and an independent 5s/8s/15s budget invented at each
layer. This gives the stack one vocabulary:

  RetryPolicy — exponential backoff with FULL JITTER (AWS-style:
    sleep ~ U(0, min(cap, base * mult^attempt))), optionally bounded by
    a max attempt count, always bounded by the caller's Deadline.

  Deadline — a monotonic-clock budget stamped ONCE at the entry point
    (query / commit / admin op) and flowed through every layer beneath:
    RemoteGroup.read/propose, RemoteZero._exec, RpcClient.call all
    clamp their per-attempt timeouts to what remains instead of
    stacking their own defaults.

  deadline_scope — thread-local propagation so the deadline crosses
    layers without threading a parameter through every signature.
    (Worker threads of the parallel executor do not inherit the scope;
    calls made there fall back to per-layer defaults.)

Retries/giveups are counted in utils/observe.METRICS
(`rpc_retries_total`, `rpc_giveups_total` are incremented by the
transports; this module only supplies the arithmetic).
"""

from __future__ import annotations

import random
import threading
import time
from contextlib import contextmanager
from typing import Optional


class Deadline:
    """An absolute point on the monotonic clock."""

    __slots__ = ("at",)

    def __init__(self, at: float):
        self.at = float(at)

    @classmethod
    def after(cls, seconds: float) -> "Deadline":
        return cls(time.monotonic() + seconds)

    def remaining(self) -> float:
        return self.at - time.monotonic()

    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def clamp(self, seconds: float, floor: float = 0.001) -> float:
        """Cap a per-attempt budget to what remains of the deadline."""
        return max(floor, min(seconds, self.remaining()))

    def __repr__(self):
        return f"Deadline(remaining={self.remaining():.3f}s)"


class RetryPolicy:
    """Exponential backoff + full jitter, deadline-aware."""

    def __init__(
        self,
        base: float = 0.02,
        mult: float = 2.0,
        cap: float = 1.0,
        max_attempts: int = 0,
        rng: Optional[random.Random] = None,
    ):
        self.base = base
        self.mult = mult
        self.cap = cap
        self.max_attempts = max_attempts  # 0 = unbounded (deadline rules)
        self.rng = rng or random.Random()

    def backoff(self, attempt: int) -> float:
        """Jittered sleep for the given 1-based attempt number."""
        ceiling = min(self.cap, self.base * (self.mult ** max(0, attempt - 1)))
        return self.rng.uniform(0.0, ceiling)

    def exhausted(self, attempt: int) -> bool:
        return bool(self.max_attempts) and attempt >= self.max_attempts

    def sleep(self, attempt: int, deadline: Optional[Deadline] = None) -> float:
        """Sleep the jittered backoff, never past the deadline. Returns
        the duration actually slept."""
        d = self.backoff(attempt)
        if deadline is not None:
            d = min(d, max(0.0, deadline.remaining()))
        if d > 0:
            time.sleep(d)
        return d


class RetryBudget:
    """A shared token budget for the retries AND hedges of ONE logical
    operation (a query, a soak step). Every layer that would re-issue an
    RPC — the outer rotation loop in RemoteGroup.read, a hedge fire, a
    retrying_call attempt — draws from the same pool, so a brownout
    (every replica slow, every call timing out) costs at most
    `tokens` extra RPCs instead of multiplying per layer into a retry
    storm. The FIRST attempt of anything is free; only re-issues spend.

    Thread-safe: hedge workers and the calling thread spend
    concurrently."""

    __slots__ = ("capacity", "_left", "_lock")

    def __init__(self, tokens: int):
        self.capacity = int(tokens)
        self._left = int(tokens)
        self._lock = threading.Lock()

    def try_spend(self, n: int = 1) -> bool:
        """Take `n` tokens; False (and takes nothing) when fewer remain."""
        with self._lock:
            if self._left < n:
                return False
            self._left -= n
            return True

    def remaining(self) -> int:
        with self._lock:
            return self._left

    def __repr__(self):
        return f"RetryBudget({self.remaining()}/{self.capacity})"


def retrying_call(
    fn,
    policy: Optional[RetryPolicy] = None,
    deadline: Optional[Deadline] = None,
    retryable: tuple = (),
    budget: Optional[RetryBudget] = None,
):
    """Run `fn()` with backoff-retry on `retryable` exception types —
    the client-side contract of the serving front's admission gate
    (TooManyRequestsError is retryable: shed fast, retry with backoff).
    Also retries any exception whose `retryable` attribute is true.
    Always bounded: the default policy caps attempts, and a policy with
    max_attempts=0 MUST come with a deadline (an unbounded retry loop
    against a persistently-shedding server would never return). With a
    `budget`, each retry additionally spends one token from the shared
    per-operation RetryBudget and the last exception re-raises when the
    pool is dry — the first attempt is always free."""
    policy = policy or RetryPolicy(base=0.005, cap=0.25, max_attempts=8)
    if not policy.max_attempts and deadline is None:
        raise ValueError(
            "retrying_call needs a bounded policy (max_attempts) or a "
            "deadline"
        )
    attempt = 0
    while True:
        try:
            return fn()
        except Exception as exc:
            is_retryable = isinstance(exc, retryable) or bool(
                getattr(exc, "retryable", False)
            )
            attempt += 1
            if not is_retryable or policy.exhausted(attempt) or (
                deadline is not None and deadline.expired()
            ):
                raise
            if budget is not None and not budget.try_spend():
                raise
            policy.sleep(attempt, deadline)


def poll_policy(interval_s: float) -> RetryPolicy:
    """Jittered fixed-cadence poll: every attempt sleeps
    uniform(0, 2*interval), so the MEAN period equals `interval_s` (the
    old fixed-sleep cadence) while concurrent pollers de-synchronize.
    The sanctioned replacement for `while ...: time.sleep(c)` loops —
    the deadline-hygiene checker flags naked sleeps in the cluster
    directories."""
    return RetryPolicy(
        base=2.0 * interval_s, mult=1.0, cap=2.0 * interval_s
    )


# ---------------------------------------------------------------------------
# thread-local deadline propagation
# ---------------------------------------------------------------------------

_TLS = threading.local()


def current_deadline() -> Optional[Deadline]:
    return getattr(_TLS, "deadline", None)


@contextmanager
def deadline_scope(deadline: Deadline):
    """Install `deadline` as the ambient budget for this thread. Nested
    scopes keep the TIGHTER deadline (an inner layer may shrink the
    budget, never extend it)."""
    prev = getattr(_TLS, "deadline", None)
    if prev is not None and prev.at < deadline.at:
        deadline = prev
    _TLS.deadline = deadline
    try:
        yield deadline
    finally:
        _TLS.deadline = prev


def effective_deadline(default_s: float) -> Deadline:
    """The ambient deadline, or a fresh one of `default_s` — the seam
    every mid-layer uses instead of inventing its own budget."""
    return current_deadline() or Deadline.after(default_s)

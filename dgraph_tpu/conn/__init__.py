"""Cluster RPC layer (ref /root/reference/conn/): pooled connections,
heartbeat health, request/response framing over TCP."""

from dgraph_tpu.conn.rpc import RpcClient, RpcError, RpcPool, RpcServer

__all__ = ["RpcClient", "RpcError", "RpcPool", "RpcServer"]

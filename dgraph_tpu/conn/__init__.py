"""Cluster RPC layer (ref /root/reference/conn/): pooled connections,
heartbeat health + circuit breaking, request/response framing over TCP,
deterministic fault injection (faults.py) and the shared
retry/deadline vocabulary (retry.py)."""

from dgraph_tpu.conn.retry import Deadline, RetryPolicy, deadline_scope
from dgraph_tpu.conn.rpc import (
    PeerDownError,
    RpcClient,
    RpcError,
    RpcPool,
    RpcServer,
)

__all__ = [
    "Deadline",
    "PeerDownError",
    "RetryPolicy",
    "RpcClient",
    "RpcError",
    "RpcPool",
    "RpcServer",
    "deadline_scope",
]

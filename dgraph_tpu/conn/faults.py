"""Deterministic fault injection for the cross-process cluster stack.

The reference proves its failure handling with Jepsen-style chaos
(systest/bank, conn/pool.go MonitorHealth recovering from flapping
peers). This module is the injection half of that story for dgraph-tpu:
a process-wide, seedable `FaultPlan` that the transports consult at
well-defined points —

  send       RpcClient, before a request frame leaves
  recv       RpcServer, on request receipt (before the handler runs)
  resp       RpcServer, before the response frame is written (a `drop`
             here models "applied but the ack was lost", the classic
             double-apply trap the idempotency LRU exists for)
  raft_send  raft/tcp.py TcpNetwork.send, per remote peer
  raft_recv  raft/tcp.py listener, per remote sender
  move.*     named in-code sync points at the tablet-move phase
             boundaries (worker/tabletmove.py via `syncpoint`): crash
             rules simulate coordinator death at exactly that boundary
             (InjectedCrash), delay rules stretch a phase
  backup.*   the backup coordinator's journaled phase boundaries
             (worker/backupdriver.py: backup.begin/group/manifest)
  cdc.*      the CDC emitter's sink-write/checkpoint boundaries
             (admin/cdc.py: cdc.emit/cdc.checkpoint — a crash here
             simulates sink death inside the at-least-once window)

Actions: drop | delay | dup | disconnect | partition | crash.
`crash` only fires at named sync points. `partition` is a
deterministic directional block (see `FaultPlan.partition`); the rest
fire probabilistically but DETERMINISTICALLY: each (point, peer) pair
is a stream with its own monotonic counter, and the n-th decision of a
stream is a pure hash of (seed, rule, stream, n) — independent of
thread scheduling, so the same seed reproduces the same per-stream
fault sequence byte-for-byte across runs (`replay` verifies this).

Activation: programmatic `install(plan)` / `reset()`, or the
`DGRAPH_TPU_FAULT_PLAN` env var (a JSON spec, or `@/path/to/spec.json`)
which child alpha/zero processes inherit from the harness. Every
injected fault increments `fault_<action>_total` / `faults_injected_total`
in utils/observe.METRICS and lands in a bounded audit log, so chaos
runs are auditable after the fact.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from collections import deque
from typing import Dict, List, Optional, Tuple

from dgraph_tpu.utils.observe import METRICS

_ACTIONS = ("drop", "delay", "dup", "disconnect", "partition", "crash")
_OUTBOUND = ("send", "raft_send")


class InjectedCrash(RuntimeError):
    """A `crash` rule fired at a named sync point: the in-process
    simulation of the coordinator dying at exactly that boundary (the
    tablet-move chaos suite drives one of these at every journaled
    phase transition). Callers must NOT catch this to clean up — a real
    SIGKILL would not have run the cleanup either; recovery code has to
    heal from the durable journal alone."""


def _peer_str(peer) -> str:
    if isinstance(peer, (tuple, list)) and len(peer) == 2:
        return f"{peer[0]}:{peer[1]}"
    return str(peer)


class FaultRule:
    """One match+action clause of a plan."""

    __slots__ = (
        "action", "point", "peer", "method", "p", "delay_ms", "after",
        "max", "fired",
    )

    def __init__(
        self,
        action: str,
        point: str = "*",
        peer: str = "*",
        method: str = "*",
        p: float = 1.0,
        delay_ms: float = 0.0,
        after: int = 0,
        max: int = 0,
    ):
        if action not in _ACTIONS:
            raise ValueError(f"unknown fault action {action!r}")
        self.action = action
        self.point = point
        self.peer = _peer_str(peer) if peer != "*" else "*"
        self.method = method
        self.p = float(p)
        self.delay_ms = float(delay_ms)
        self.after = int(after)  # skip the first N decisions of a stream
        self.max = int(max)      # total fires across all streams (0 = inf)
        self.fired = 0

    @property
    def delay_s(self) -> float:
        return self.delay_ms / 1000.0

    def matches(self, point: str, peer: str, method: str) -> bool:
        return (
            self.point in ("*", point)
            and self.peer in ("*", peer)
            and self.method in ("*", method)
        )

    def to_dict(self) -> dict:
        return {
            "action": self.action, "point": self.point, "peer": self.peer,
            "method": self.method, "p": self.p, "delay_ms": self.delay_ms,
            "after": self.after, "max": self.max,
        }


class _Partition(FaultRule):
    """Synthetic rule returned for a blocked (partitioned) peer."""

    def __init__(self):
        super().__init__("partition")


_PARTITION = _Partition()


class FaultPlan:
    """Seeded, process-wide fault schedule. Thread-safe."""

    def __init__(self, seed: int = 0, rules: Optional[List[dict]] = None,
                 log_cap: int = 4096):
        self.seed = int(seed)
        self.rules: List[FaultRule] = [
            r if isinstance(r, FaultRule) else FaultRule(**r)
            for r in (rules or [])
        ]
        self._lock = threading.Lock()
        self._counts: Dict[Tuple[str, str], int] = {}
        self._blocked: set = set()  # ("to"|"from", peer_str)
        self.log: deque = deque(maxlen=log_cap)

    # -- partitions ------------------------------------------------------

    def partition(self, peer, direction: str = "both"):
        """Deterministically block traffic with `peer`. direction:
        "to" (we stop sending), "from" (we stop receiving), "both"."""
        p = _peer_str(peer)
        with self._lock:
            if direction in ("to", "both"):
                self._blocked.add(("to", p))
            if direction in ("from", "both"):
                self._blocked.add(("from", p))

    def heal(self, peer=None):
        """Lift partitions — for `peer`, or all when None."""
        with self._lock:
            if peer is None:
                self._blocked.clear()
            else:
                p = _peer_str(peer)
                self._blocked -= {("to", p), ("from", p)}

    def _is_blocked(self, point: str, peer: str) -> bool:
        d = "to" if point in _OUTBOUND else "from"
        return (d, peer) in self._blocked

    # -- decisions -------------------------------------------------------

    def _draw(self, rule_idx: int, stream: Tuple[str, str], n: int) -> float:
        h = hashlib.blake2b(
            f"{self.seed}|{rule_idx}|{stream[0]}|{stream[1]}|{n}".encode(),
            digest_size=8,
        ).digest()
        return int.from_bytes(h, "big") / float(1 << 64)

    def _pick(self, stream: Tuple[str, str], n: int, method: str,
              count_max: bool) -> Optional[FaultRule]:
        """Pure rule evaluation for decision n of a stream (1-based)."""
        point, peer = stream
        for idx, r in enumerate(self.rules):
            if not r.matches(point, peer, method):
                continue
            if n <= r.after:
                continue
            if count_max and r.max and r.fired >= r.max:
                continue
            if r.p >= 1.0 or self._draw(idx, stream, n) < r.p:
                return r
        return None

    def decide(self, point: str, peer, method: str = "") -> Optional[FaultRule]:
        """Advance the (point, peer) stream and return the fault to
        inject for this event, or None. Deterministic per stream."""
        peer_s = _peer_str(peer)
        stream = (point, peer_s)
        with self._lock:
            n = self._counts[stream] = self._counts.get(stream, 0) + 1
            if self._is_blocked(point, peer_s):
                self.log.append((point, peer_s, n, "partition", method))
                METRICS.inc("faults_injected_total")
                METRICS.inc("fault_partition_total")
                return _PARTITION
            r = self._pick(stream, n, method, count_max=True)
            if r is None:
                return None
            r.fired += 1
            self.log.append((point, peer_s, n, r.action, method))
        METRICS.inc("faults_injected_total")
        METRICS.inc(f"fault_{r.action}_total")
        return r

    def replay(self, point: str, peer, upto: int,
               method: str = "") -> List[Optional[str]]:
        """Recompute decisions 1..upto for a stream WITHOUT advancing
        state — the reproducibility witness (valid for plans whose rules
        carry no `max` cap, since `fired` is cross-stream state)."""
        stream = (point, _peer_str(peer))
        return [
            (r.action if r is not None else None)
            for n in range(1, upto + 1)
            for r in (self._pick(stream, n, method, count_max=False),)
        ]

    def trace(self) -> Dict[Tuple[str, str], List[Tuple[int, str]]]:
        """Injected faults grouped per stream: {(point, peer): [(n, action)]}.
        Per-stream sequences are deterministic for a given seed."""
        out: Dict[Tuple[str, str], List[Tuple[int, str]]] = {}
        with self._lock:
            for point, peer, n, action, _m in self.log:
                out.setdefault((point, peer), []).append((n, action))
        for seq in out.values():
            seq.sort()
        return out

    def counts(self) -> Dict[Tuple[str, str], int]:
        with self._lock:
            return dict(self._counts)

    def to_spec(self) -> dict:
        return {"seed": self.seed, "rules": [r.to_dict() for r in self.rules]}


# ---------------------------------------------------------------------------
# process-wide activation
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_ACTIVE: Optional[FaultPlan] = None

# single source: the registry owns the variable's name and doc
from dgraph_tpu.x import config as _config

ENV_VAR = _config.knob("FAULT_PLAN").env


def _plan_from_env() -> Optional[FaultPlan]:
    spec = _config.get("FAULT_PLAN").strip()
    if not spec:
        return None
    if spec.startswith("@"):
        with open(spec[1:]) as f:
            spec = f.read()
    obj = json.loads(spec)
    return FaultPlan(seed=obj.get("seed", 0), rules=obj.get("rules") or [])


def install(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Set (or clear, with None) the process-wide plan."""
    global _ACTIVE
    with _LOCK:
        _ACTIVE = plan
    return plan


def reset():
    install(None)


def active() -> Optional[FaultPlan]:
    return _ACTIVE


def init_from_env(force: bool = False) -> Optional[FaultPlan]:
    """Load the env-specified plan (alpha/zero processes call this at
    startup so a harness-exported schedule applies inside replicas).
    Without `force`, an already-installed plan wins."""
    global _ACTIVE
    with _LOCK:
        if _ACTIVE is not None and not force:
            return _ACTIVE
        _ACTIVE = _plan_from_env()
        return _ACTIVE


def syncpoint(point: str, peer="coordinator"):
    """Named in-code fault point (the tablet-move phase boundaries
    `move.begin`/`copy`/`chunk`/`fence`/`delta`/`flip`/`drop`, the
    backup coordinator's `backup.begin`/`group`/`manifest`, and the
    CDC emitter's `cdc.emit`/`cdc.checkpoint`). Consults the active
    plan's deterministic per-(point, peer) stream like any transport
    hook:

      crash  -> raises InjectedCrash (simulated coordinator death at
                exactly this boundary; the caller must not clean up)
      delay  -> sleeps delay_ms (stretches a phase deterministically so
                concurrency tests can observe it in flight)

    Other actions are transport-only and ignored here. Plans with no
    rule matching the point leave its stream untouched, so installing a
    move-point schedule never perturbs the RPC/raft stream draws."""
    plan = _ACTIVE
    if plan is None:
        return
    peer_s = _peer_str(peer)
    if not any(
        r.matches(point, peer_s, "") and r.action in ("crash", "delay")
        for r in plan.rules
    ):
        return
    r = plan.decide(point, peer, "")
    if r is None:
        return
    if r.action == "crash":
        raise InjectedCrash(f"{point} ({peer_s})")
    if r.action == "delay" and r.delay_s > 0:
        import time as _time

        _time.sleep(r.delay_s)  # injected latency, not a retry backoff


# child processes inherit the harness env: pick the plan up at import so
# every transport in the replica consults it from the first frame
init_from_env()

"""Superflags: grouped `k=v; k2=v2` option strings.

Mirrors /root/reference/x/flags.go (NewSuperFlag / GetString etc.): the
reference's CLIs take option groups like
  --badger "compression=zstd; numgoroutines=8"
  --security "whitelist=10.0.0.0/8; token=abc"
with defaults merged and unknown keys rejected. Same contract here for
the alpha/bulk CLIs (--storage, --security, --trace, --raft, --limit).
"""

from __future__ import annotations

from typing import Dict, Optional


class SuperFlagError(ValueError):
    pass


class SuperFlag:
    def __init__(self, spec: str = "", defaults: str = ""):
        """spec: user input "k=v; k2=v2"; defaults defines the allowed
        keys AND their default values (like NewSuperFlag(...).MergeAndCheck)."""
        self._defaults = self._parse(defaults)
        given = self._parse(spec)
        unknown = set(given) - set(self._defaults)
        if self._defaults and unknown:
            raise SuperFlagError(
                f"unknown superflag option(s) {sorted(unknown)}; "
                f"allowed: {sorted(self._defaults)}"
            )
        self._vals: Dict[str, str] = dict(self._defaults)
        self._vals.update(given)

    @staticmethod
    def _parse(s: str) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for part in (s or "").split(";"):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise SuperFlagError(f"superflag option {part!r} needs k=v")
            k, v = part.split("=", 1)
            out[k.strip().lower().replace("_", "-")] = v.strip()
        return out

    def get_string(self, key: str, default: str = "") -> str:
        return self._vals.get(key.lower().replace("_", "-"), default)

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self.get_string(key, "")
        if v == "":
            return default
        if v.lower() in ("true", "1", "yes", "on"):
            return True
        if v.lower() in ("false", "0", "no", "off"):
            return False
        raise SuperFlagError(f"superflag {key}={v!r} is not a boolean")

    def get_int(self, key: str, default: int = 0) -> int:
        v = self.get_string(key, "")
        if v == "":
            return default
        try:
            return int(v)
        except ValueError as e:
            raise SuperFlagError(f"superflag {key}={v!r} is not an int") from e

    def get_float(self, key: str, default: float = 0.0) -> float:
        v = self.get_string(key, "")
        if v == "":
            return default
        try:
            return float(v)
        except ValueError as e:
            raise SuperFlagError(
                f"superflag {key}={v!r} is not a float"
            ) from e

    def as_dict(self) -> Dict[str, str]:
        return dict(self._vals)


# the alpha CLI's groups (subset of dgraph alpha's; ref worker/config.go)
STORAGE_DEFAULTS = "backend=mem; encryption-key-file=; memtable-mb=8"
SECURITY_DEFAULTS = "token=; whitelist="
TRACE_DEFAULTS = "jaeger=; datadog=; ratio=0.01; sink-file="
LIMIT_DEFAULTS = (
    "query-edge=1000000; mutations=allow; max-retries=-1; "
    "max-pending-queries=10000"
)
RAFT_DEFAULTS = "compact-every=1024; election-lo-ms=150; election-hi-ms=300"

from dgraph_tpu.x.keys import (
    DataKey,
    IndexKey,
    ReverseKey,
    CountKey,
    SchemaKey,
    TypeKey,
    parse_key,
    ParsedKey,
)

"""Typed registry for every `DGRAPH_TPU_*` environment knob.

Before this module each knob was a raw `os.environ.get` at its call
site, with the default duplicated (and free to drift) per site and no
single place documenting what exists. This registry is now the ONLY
sanctioned reader of `DGRAPH_TPU_*` variables — the static-analysis
suite (`dgraph_tpu/analysis`, `dgraph-tpu lint`) flags any raw
`os.environ` / `os.getenv` access elsewhere in the package.

Contract:

  - Every knob is declared ONCE here with (name, type, default, doc).
  - `get("NAME")` reads `DGRAPH_TPU_<NAME>` from the environment,
    parses it to the declared type, and falls back to the declared
    default when unset OR unparseable (a malformed value must never
    crash a server at import time).
  - Booleans accept 1/true/yes/on and 0/false/no/off (case-insensitive);
    anything else falls back to the default.
  - `reference_table()` renders the whole registry as the Markdown
    table checked in at CONFIG.md (tests assert the file is in sync).

Call sites keep their own read-at-import vs read-per-call timing; this
module only centralizes the parse + default.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Dict, Optional

PREFIX = "DGRAPH_TPU_"

_TRUE = frozenset({"1", "true", "yes", "on"})
_FALSE = frozenset({"0", "false", "no", "off"})


@dataclass(frozen=True)
class Knob:
    name: str  # short name; env var is PREFIX + name
    type: str  # "str" | "int" | "float" | "bool"
    default: Any
    doc: str

    @property
    def env(self) -> str:
        return PREFIX + self.name

    def parse(self, raw: str) -> Any:
        """Parse a raw env string; raises ValueError when malformed."""
        if self.type == "str":
            return raw
        if self.type == "bool":
            v = raw.strip().lower()
            if v in _TRUE:
                return True
            if v in _FALSE:
                return False
            raise ValueError(f"{self.env}={raw!r} is not a boolean")
        if self.type == "int":
            return int(raw.strip())
        if self.type == "float":
            return float(raw.strip())
        raise ValueError(f"unknown knob type {self.type!r}")


REGISTRY: Dict[str, Knob] = {}


def _define(name: str, type_: str, default: Any, doc: str) -> Knob:
    if name in REGISTRY:
        raise ValueError(f"duplicate knob {name!r}")
    k = Knob(name=name, type=type_, default=default, doc=doc)
    REGISTRY[name] = k
    return k


# ---------------------------------------------------------------------------
# knob declarations (one line per knob; keep alphabetical)
# ---------------------------------------------------------------------------

_define(
    "ADMISSION", "bool", False,
    "Admission control at the query entry points (serving/admission.py): "
    "estimated query cost is charged against DGRAPH_TPU_MAX_INFLIGHT "
    "tokens; over-budget arrivals are shed fast with a retryable "
    "too_many_requests error (HTTP 429), and arrivals during saturation "
    "(slow-query signal or exec-pool backpressure) run degraded — "
    "bounded budget, partial response — instead of queueing. Off by "
    "default; the in-flight gauge is tracked regardless.",
)
_define(
    "APPLY_PROCS", "str", "auto",
    "Multi-process apply shards behind the raft apply loop "
    "(worker/applyshard.py): the group-commit columnar write-set is "
    "partitioned by (namespace, predicate) and shipped over per-worker "
    "shared-memory rings to this many apply-shard worker processes, "
    "whose batch_apply kernels run outside the serving interpreter's "
    "GIL. 'auto' resolves to cpu_count-1; 0 is the in-process escape "
    "hatch (the kernel runs on the committing thread, exactly the "
    "pre-proc path).",
)
_define(
    "APPLY_PROC_TIMEOUT_MS", "int", 5000,
    "Per-batch deadline (ms) for an apply-shard worker process to "
    "return its encoded shard (worker/applyshard.py): a worker that "
    "blows it is killed and respawned, and the batch replays through "
    "the in-process kernel with exact serial semantics "
    "(apply_shard_fallback_total{reason=\"timeout\"}).",
)
_define(
    "APPLY_RING_BYTES", "int", 16 << 20,
    "Size of each apply-shard worker's shared-memory ring "
    "(worker/applyshard.py): one flat request/response region the "
    "columnar batch columns are memcpy'd into (no pickling of edges). "
    "A batch whose columns or encoded output exceed it falls back to "
    "the in-process kernel (reason=\"ring_full\").",
)
_define(
    "APPLY_SHARDS", "int", 0,
    "Predicate-sharded residual mutation apply (posting/mutation.py "
    "_apply_edges_sharded): edges that escape the columnar kernel are "
    "partitioned by (namespace, predicate) and applied concurrently on "
    "the exec-worker pool, merged back deterministically in shard-index "
    "order (all key kinds embed the attr, so shards touch disjoint "
    "keys). 0 (default) = automatic — shard when EXEC_WORKERS >= 2 and "
    "the call clears DGRAPH_TPU_APPLY_SHARD_MIN_EDGES; 1 forces the "
    "serial path; N>1 forces up to N shards regardless of size.",
)
_define(
    "APPLY_SHARD_MIN_EDGES", "int", 64,
    "Minimum edges in one apply_edges call before the automatic "
    "predicate-sharding heuristic engages (posting/mutation.py): below "
    "this, thread handoff costs more than the GIL-released tokenizer "
    "work the shards would overlap.",
)
_define(
    "BACKUP_CHUNK_BYTES", "int", 4 << 20,
    "Byte bound on one backup chunk file's (uncompressed) record "
    "payload (admin/backup.py BackupWriter): a tablet of any size "
    "streams into bounded, individually-verifiable files instead of "
    "one unbounded stream a torn write could silently shorten.",
)
_define(
    "BATCH_APPLY", "bool", True,
    "Columnar native mutation apply (posting/colwrite.py + codec.cpp "
    "batch_apply): fast-shape SET edges (scalar values with "
    "exact/int/bool/term indexes, list-uid incl. @reverse) are "
    "collected as columns instead of Posting objects and encoded at "
    "commit by ONE native call per group-commit batch — fused "
    "tokenization, index/reverse key emission and delta-record "
    "encoding, byte-identical to the serial path. Ineligible edges "
    "materialize back through the serial path automatically. 0 "
    "restores the per-edge Python apply everywhere — the A/B escape "
    "hatch.",
)
_define(
    "BATCH_WINDOW_US", "int", 0,
    "Cross-query micro-batching (serving/microbatch.py): same-shape "
    "(predicate, level) tasks from different in-flight queries that "
    "arrive DURING an in-flight same-shape dispatch coalesce into the "
    "next combined level read, demuxed per query on return (natural "
    "batching: an idle shape dispatches immediately with zero added "
    "latency). The value caps, in microseconds, how long a forming "
    "batch waits for the dispatch ahead of it. 0 (default) disables "
    "the batcher entirely — the executor takes the direct path.",
)
_define(
    "BITMAP_BLOCK_BITS", "int", 2048,
    "Fixed bitset size (bits, rounded up to a multiple of 64) for the "
    "per-block bitmap containers: a UidPack block whose uid range fits "
    "and whose density clears 1/8 materializes as a bitset and runs the "
    "word-wise AND/ANDNOT kernels (codec/uidpack.py, native/codec.cpp); "
    "dense blocks also serialize as raw bitsets. 0 disables bitmap "
    "containers entirely — use in a mixed-version store, since records "
    "holding bitmap blocks are unreadable by pre-bitmap builds.",
)
_define(
    "BULK_NATIVE", "bool", True,
    "Use the native C++ map/reduce pipeline for offline bulk loads when "
    "the compiled library is available (loaders/bulk2.py). Disable to "
    "force the pure-Python slow path.",
)
_define(
    "CDC_QUEUE_MAX", "int", 4096,
    "Bounded CDC event queue (admin/cdc.py): commits enqueue their "
    "events here for the sink-emitter thread; a full queue blocks the "
    "committer (backpressure) until the sink drains, so an event can "
    "never be silently dropped while the process lives. Sink-crash "
    "loss windows are closed by replay-from-checkpoint at startup.",
)
_define(
    "CDC_SINK", "str", "",
    "Default CDC sink URI for `dgraph-tpu alpha`/`cdc` when no "
    "explicit sink is given: a file path / file:// URI (ndjson), or "
    "kafka://host:port/topic when kafka-python is installed "
    "(admin/handlers.py sink_for). Empty = CDC disabled unless "
    "enabled explicitly.",
)
_define(
    "COMMIT_DEADLINE_S", "float", 20.0,
    "Budget stamped on a commit at the ProcCluster entry point; flows "
    "through zero.commit and every group proposal beneath it "
    "(worker/harness.py).",
)
_define(
    "DEBUG_HTTP", "bool", True,
    "Serve /debug/prometheus_metrics + /debug/traces over HTTP from "
    "every alpha/zero replica process (ephemeral port, discoverable "
    "via the debug.info RPC). 0 disables the per-process listener.",
)
_define(
    "DEVCACHE_BYTES", "int", 256 << 20,
    "LRU bound, in device bytes, for the HBM operand cache "
    "(query/dispatch.py DeviceCache).",
)
_define(
    "DEVICE_INIT_TIMEOUT_S", "float", 120.0,
    "Watchdog on first jax backend init; on timeout the dispatcher "
    "degrades permanently to host kernels (query/dispatch.py).",
)
_define(
    "DEVICE_MIN_TOTAL", "int", None,
    "Min combined operand size routed to the device kernels. Unset = "
    "backend-aware auto (host-only on cpu backends, 1<<15 on TPU); "
    "0 means ALWAYS use the device (query/dispatch.py).",
)
_define(
    "DIGEST", "bool", True,
    "Always-on query digest store (serving/digest.py): per-(namespace, "
    "normalized-shape) aggregate statistics — calls, errors, latency "
    "histogram, result rows/bytes, plan/result-cache hits, packed-"
    "kernel deltas — fed at the query entry points and served at "
    "/debug/digests (the pg_stat_statements analog). 0 disables the "
    "accounting — the flight-recorder A/B escape hatch.",
)
_define(
    "DIGEST_SHAPES", "int", 512,
    "Digest-store capacity in distinct (namespace, shape) rows "
    "(serving/digest.py); LRU beyond it, with evicted rows folded "
    "into the sticky per-namespace `other` bucket so totals stay "
    "exact under churn.",
)
_define(
    "EXEC_WORKERS", "int", 0,
    "Sibling fan-out width for the parallel query executor; 0/1 = "
    "serial escape hatch (query/subgraph.py). Re-read per Executor so "
    "tests can flip it between queries.",
)
_define(
    "EXEMPLARS", "bool", True,
    "Trace exemplars on latency histograms: each histogram bucket "
    "retains its latest (value, trace_id) observation, exported in "
    "OpenMetrics exemplar syntax at /debug/openmetrics and embedded in "
    "slow-query log records — the metrics→trace link "
    "(utils/observe.py). 0 disables exemplar capture.",
)
_define(
    "FAKE_NOW", "str", "",
    "Frozen timestamp for @default($now) GraphQL values — test "
    "determinism hook (graphql/resolve.py). Empty = real UTC now.",
)
_define(
    "FAULT_PLAN", "str", "",
    "Deterministic fault-injection plan: inline JSON or @/path/to/file "
    "(conn/faults.py). Inherited by alpha/zero replica processes.",
)
_define(
    "FORCE_CPU", "bool", False,
    "Unregister the remote-TPU backend and pin jax to the CPU platform "
    "before first backend init (devsetup.maybe_force_cpu).",
)
_define(
    "FORCE_DEVICE", "bool", False,
    "Route every set op to the device kernels regardless of size "
    "thresholds (query/dispatch.py) — benchmarking hook.",
)
_define(
    "FOLLOWER_READS", "bool", True,
    "Watermark-verified follower read routing (worker/remote.py, "
    "worker/groups.py): read-only calls may be served by any replica "
    "whose raft applied index covers the query's snapshot watermark "
    "(PR 11 rule — provably byte-identical), picked by latency EWMA "
    "with a per-replica circuit breaker; a leaderless group keeps "
    "serving watermark reads marked `degraded: leaderless` (only once "
    "the read floor is KNOWN — a restarted coordinator serves "
    "leader-only until a leader reply/proposal re-establishes it). 0 "
    "restores strict leader-first routing: the blind follower hedge "
    "on the remote plane, leader-only in-proc.",
)
_define(
    "FOLLOWER_READ_TTL_S", "float", 0.5,
    "Freshness window for a replica's cached applied-index/health row "
    "(worker/replicapick.py): a follower whose row is older than this "
    "is skipped (stale-or-unknown never serves) and a background "
    "re-probe is kicked off.",
)
_define(
    "GROUP_COMMIT", "bool", True,
    "Group-commit write pipeline (worker/groupcommit.py): concurrent "
    "committers coalesce into batches that share ONE oracle verdict "
    "exchange and ONE bounded raft proposal per owning group, with the "
    "snapshot watermark advanced in commit-ts order. 0 restores the "
    "serial per-txn commit path byte-for-byte (the A/B escape hatch).",
)
_define(
    "GROUP_COMMIT_BYPASS", "bool", True,
    "Adaptive group-commit bypass (worker/groupcommit.py): when the "
    "realized batch-width EWMA is ~1 (no batchmate is ever waiting) a "
    "committer that finds the coalescer completely idle commits "
    "straight through the engine's serial path, skipping the "
    "queue/ticket/condvar handoffs that measurably lose to serial at "
    "width ~1.05. Concurrency re-engages coalescing automatically "
    "(an arrival during a bypass or a busy leader always queues). 0 "
    "forces every commit through the coalescer (the A/B escape "
    "hatch).",
)
_define(
    "GROUP_COMMIT_MAX_TXNS", "int", 64,
    "Cap on transactions coalesced into one commit batch "
    "(worker/groupcommit.py); excess committers form the next batch.",
)
_define(
    "GROUP_COMMIT_WINDOW_US", "int", 200,
    "Extra microseconds a commit-batch leader waits for more "
    "committers to arrive — only while an earlier batch's apply "
    "barrier is still in flight (an idle engine always commits "
    "immediately, like the PR 7 batcher's natural batching). 0 "
    "disables the wait; batches still form from whatever is queued.",
)
_define(
    "HISTORY", "bool", True,
    "Metrics history ring (utils/observe.py MetricsHistory): a "
    "background sampler snapshots every counter/gauge + histogram "
    "sum/count once per HISTORY_INTERVAL_S into a bounded in-memory "
    "ring, so windowed deltas (/debug/history?window=) are computable "
    "after an incident without reruns. 0 disables sampling — the "
    "flight-recorder A/B escape hatch.",
)
_define(
    "HISTORY_DIR", "str", "",
    "When set, history snapshots are also appended to an on-disk ring "
    "(history-<instance|pid>.log inside this directory) in the shared "
    "AppendLog record format — torn tails truncated at open, rotation "
    "at HISTORY_DISK_MAX_BYTES — so the recorded window survives a "
    "process restart. Empty = in-memory only.",
)
_define(
    "HISTORY_DISK_MAX_BYTES", "int", 8 << 20,
    "Rotation bound for the on-disk history ring: past it the file is "
    "rewritten keeping the newest half of its records (the slow-query-"
    "log hysteresis, amortized rewrites).",
)
_define(
    "HISTORY_INTERVAL_S", "float", 60.0,
    "Seconds between metrics-history snapshots (minute buckets by "
    "default; tests dial it down).",
)
_define(
    "HISTORY_RETENTION", "int", 180,
    "In-memory history snapshots retained (oldest dropped beyond it): "
    "180 x 60s = a 3h window at the default interval.",
)
_define(
    "LAMBDA_URL", "str", "",
    "GraphQL @lambda resolver endpoint; the alpha CLI superflag takes "
    "precedence (graphql/resolve.py).",
)
_define(
    "LEVEL_BATCH", "bool", True,
    "Level-batched task reads (uids_many/values_many, one MemoryLayer "
    "pass per level). 0 = per-uid escape hatch for A/B benchmarking "
    "(query/subgraph.py).",
)
_define(
    "MAX_FRAME_BYTES", "int", 256 << 20,
    "Hard cap on a single wire frame on BOTH the RPC and raft planes; "
    "a corrupt length prefix must never drive an unbounded allocation "
    "(conn/frame.py, matches the reference's 256MB gRPC cap).",
)
_define(
    "MAX_INFLIGHT", "int", 64,
    "Admission-control in-flight budget, in cost tokens (one token ~ "
    "10ms of observed shape latency; selectivity and pool backpressure "
    "add more). Arrivals that would push the in-flight cost past this "
    "are shed with too_many_requests when DGRAPH_TPU_ADMISSION is on "
    "(serving/admission.py).",
)
_define(
    "MAX_PART_UIDS", "int", 1 << 20,
    "Multi-part posting list threshold: a rollup whose uid set exceeds "
    "this splits into part records. ONE default shared by the runtime "
    "split (posting/pl.py) and the native bulk reduce (loaders/"
    "bulk2.py) — these previously duplicated the constant per site.",
)
_define(
    "MEMLAYER_ENTRIES", "int", 400_000,
    "MemoryLayer LRU capacity (decoded posting lists). Must exceed the "
    "touched-key count of one large traversal level or the LRU "
    "thrashes (posting/memlayer.py).",
)
_define(
    "MOVE_CHUNK_BYTES", "int", 4 << 20,
    "Byte bound on one ('delta', chunk) proposal — and on one paged "
    "source-read response — during a phased tablet move "
    "(worker/tabletmove.py): a tablet of any size streams in bounded "
    "chunks instead of one frame-cap-tripping proposal. Must stay "
    "under DGRAPH_TPU_MAX_FRAME_BYTES.",
)
_define(
    "MOVE_FENCE_DEADLINE_S", "float", 10.0,
    "Budget for a tablet move's Phase-2 fence (moving state + delta "
    "catch-up + ownership flip, under the commit lock). A delta stream "
    "that overruns it aborts and rolls the move back, so the fence can "
    "never wedge writers indefinitely (worker/tabletmove.py).",
)
_define(
    "NATIVE_CACHE", "str", None,
    "Directory holding the compiled native kernel library "
    "(native/__init__.py); keyed by source hash + sanitizer mode. "
    "Unset = <system tempdir>/dgraph_tpu_native.",
)
_define(
    "NATIVE_SAN", "str", "",
    "Sanitizer build mode for the native library: 'asan', 'tsan' or "
    "'ubsan' compile the .so with the matching -fsanitize= flags under "
    "a separate cache key; empty = plain -O3. asan/tsan need the "
    "runtime preloaded (LD_PRELOAD=$(g++ -print-file-name=libasan.so / "
    "libtsan.so)) — tests/test_native_san.py and tools/check.sh "
    "--san-matrix handle this (native/__init__.py).",
)
_define(
    "PACKED_MIN_RATIO", "int", 8,
    "Packed-vs-decode crossover for array x pack pairs: the op takes "
    "the compressed-domain path when |big| >= ratio * |small| (query/"
    "dispatch.py; tuned via TUNE_PACKED_CPU.json — 8 with the native "
    "adaptive block engine, down from the pre-engine 256). Pack x pack "
    "pairs bypass the gate entirely (the pair engine holds break-even-"
    "or-better at every ratio with zero decode); without the native "
    "engine an unset knob falls back to the pre-engine cliff of 256.",
)
_define(
    "PALLAS", "bool", False,
    "Opt-in Pallas compare-all sweep for small-side intersect buckets "
    "(query/dispatch.py, ops/pallas_setops.py).",
)
_define(
    "PLAN_CACHE_SIZE", "int", 512,
    "Plan-cache capacity in distinct normalized query shapes (serving/"
    "plancache.py); each shape holds a bounded set of literal-binding "
    "variants whose parsed trees skip parse entirely on a hit. Entries "
    "are invalidated by commit epoch (no plan survives a commit "
    "unrevalidated). 0 disables plan caching; per-shape cost stats for "
    "admission are disabled with it.",
)
_define(
    "PROFILE_AUTO", "bool", True,
    "Auto-trigger the sampling profiler on sustained SLO burn "
    "(utils/profiler.py): when the 300s query burn rate exceeds "
    "PROFILE_BURN at a history tick, a PROFILE_AUTO_S capture runs in "
    "the background and is retained for /debug/profile?last=1 — the "
    "GIL-bound residual gets attributed while it is happening. 0 "
    "disables auto-triggering (on-demand captures still work).",
)
_define(
    "PROFILE_AUTO_S", "float", 5.0,
    "Duration, in seconds, of an auto-triggered profiler capture.",
)
_define(
    "PROFILE_BURN", "float", 2.0,
    "SLO burn-rate threshold (300s window) past which the profiler "
    "auto-triggers; burn 1.0 = exactly consuming the error budget.",
)
_define(
    "PROFILE_COOLDOWN_S", "float", 600.0,
    "Minimum seconds between auto-triggered profiler captures — one "
    "sustained incident must not stack samplers.",
)
_define(
    "PROFILE_HZ", "int", 100,
    "Sampling frequency of the wall-clock profiler "
    "(utils/profiler.py): sys._current_frames() walks per second "
    "while a capture is active. The sampler runs ONLY during a "
    "capture; idle cost is zero.",
)
_define(
    "QUERY_DEADLINE_S", "float", 15.0,
    "Budget stamped on a query at the ProcCluster entry point; flows "
    "through every remote read beneath it (worker/harness.py).",
)
_define(
    "QUERY_PLANNER", "bool", True,
    "Cost-based query planner (query/planner.py): orders AND-filter "
    "chains and var-free sibling expansion cheapest-first from "
    "StatsHolder selectivity + observed-cardinality EWMAs, narrows "
    "later filter arms with the running intersection, and pushes "
    "index-answerable level filters below the fan-out when the match "
    "set is estimated smaller than the frontier. Observation-"
    "equivalent by construction (golden-corpus-enforced byte "
    "identity); 0 restores declaration-order execution — the A/B "
    "escape hatch.",
)
_define(
    "RACE_FUZZ", "bool", False,
    "GIL-fuzz race harness: when set, tests/conftest.py pins "
    "sys.setswitchinterval(1e-6) so the interpreter forces a thread "
    "switch roughly every bytecode, surfacing latent Python-level "
    "races in the fixed-seed concurrency suites deterministically "
    "instead of once a month under full-suite load. Run via "
    "tools/check.sh --race-sanity.",
)
_define(
    "READ_BREAKER_ERRORS", "int", 3,
    "Consecutive read failures that trip a replica's read-plane "
    "circuit breaker OPEN (worker/replicapick.py); an open replica is "
    "skipped by the picker until a jittered half-open probe succeeds. "
    "0 disables the breaker (every replica always eligible).",
)
_define(
    "READ_BREAKER_PROBE_S", "float", 1.0,
    "Mean interval between half-open probes of an OPEN read-plane "
    "breaker (worker/replicapick.py); each probe window is jittered "
    "uniform(0.5x, 1.5x) so a fleet of coordinators de-synchronizes. "
    "Bounds the availability gap after a replica dies: within ~one "
    "probe interval traffic has routed around it.",
)
_define(
    "READ_RETRY_BUDGET", "int", 16,
    "Per-query retry/hedge token budget (conn/retry.py RetryBudget, "
    "carried on the ReadContext): every group-read retry and every "
    "hedge fire across the whole query spends one token, so a "
    "brownout costs at most this many extra RPCs instead of "
    "multiplying per layer. Exhaustion surfaces as a retryable 503 "
    "(read_retry_budget_exhausted_total). 0 disables budgeting.",
)
_define(
    "REBALANCE_BY_TRAFFIC", "bool", False,
    "Auto-rebalance scoring mode: when on, the tablet picker weighs "
    "each tablet by size PLUS its observed traffic (decoded/result "
    "bytes served, mutation-edge volume, from the per-tablet traffic "
    "accumulator), so a hot small tablet can outweigh a cold giant "
    "one (worker/tabletmove.py pick_rebalance_move_by_traffic). Off "
    "by default: size-based rebalance stays the deterministic "
    "baseline.",
)
_define(
    "REBALANCE_INTERVAL_S", "float", 480.0,
    "Mean period of the jittered auto-rebalance loop "
    "(enable_auto_rebalance: each tick heals journaled half-moves, "
    "then takes one size-based tablet move when it narrows the "
    "byte-load gap; uniform(0, 2i) jitter de-synchronizes a fleet). "
    "Matches the reference Zero's ~8-minute rebalance cadence "
    "(zero/tablet.go).",
)
_define(
    "RESULT_CACHE_SIZE", "int", 0,
    "Snapshot-keyed whole-response result cache (serving/"
    "resultcache.py), in entries: responses are keyed on (normalized "
    "plan shape, literal bindings, variables, namespace, snapshot "
    "watermark), so a cached entry is provably byte-identical to "
    "re-execution until a commit advances the watermark — the PR 7/11 "
    "watermark proof (two reads covering the same watermark see "
    "identical stores). 0 (default) disables result reuse, like the "
    "other serving-front gates (ADMISSION, BATCH_WINDOW_US).",
)
_define(
    "RESULT_CACHE_BYTES", "int", 64 << 20,
    "Byte bound on the result cache's stored response payloads "
    "(serving/resultcache.py): eviction runs until BOTH the entry "
    "count (RESULT_CACHE_SIZE) and this byte total are under bound, "
    "so wide-fan-out responses cannot grow the cache past what the "
    "operator sized. 0 disables the byte bound (entry count only).",
)
_define(
    "RESULT_CACHE_TTL_S", "float", 300.0,
    "Age bound on a result-cache entry (serving/resultcache.py): "
    "entries older than this are treated as misses even at an "
    "unchanged watermark (a safety valve for long write-idle "
    "deployments, not a correctness requirement — watermark keying "
    "already guarantees freshness). 0 disables the TTL.",
)
_define(
    "SHARD_MIN_B", "int", 1 << 22,
    "A shared operand at/above this byte size is row-sharded over the "
    "device mesh when >1 device is visible (query/dispatch.py).",
)
_define(
    "SHARD_VECTORS", "bool", False,
    "Row-shard vector similarity corpora over the device mesh "
    "(models/vector.py + parallel/mesh.py sharded_topk).",
)
_define(
    "SKIP_REMOTE_INTROSPECTION", "bool", False,
    "Defer @custom(http:{graphql:...}) remote-endpoint introspection "
    "at schema-update time — air-gapped loads (graphql/resolve.py).",
)
_define(
    "SLO_QUERY_MS", "float", 250.0,
    "SLO latency objective in milliseconds for the entry-point "
    "latency histograms (query_latency_seconds / "
    "commit_latency_seconds): operations slower than this count "
    "against the error budget in the multi-window burn rates served "
    "at /debug/healthz (utils/observe.py SloWindows).",
)
_define(
    "SLO_TARGET", "float", 0.99,
    "SLO availability target (fraction of operations meeting "
    "DGRAPH_TPU_SLO_QUERY_MS): the error budget is 1 - target, and a "
    "window's burn rate is its error rate divided by that budget "
    "(burn 1.0 = consuming budget exactly) (utils/observe.py).",
)
_define(
    "SLOW_QUERY_LOG", "str", "",
    "Path of the bounded slow-query JSONL log (utils/observe.py "
    "SlowQueryLog). Empty = slow operations fall back to a logging "
    "warning; records carry the query text, latency, trace id, and the "
    "force-sampled local span tree.",
)
_define(
    "SLOW_QUERY_LOG_MAX", "int", 1000,
    "Record cap on the slow-query log; once exceeded the file is "
    "rewritten keeping the newest N/2 (hysteresis amortizes the "
    "rewrite over bursts) (utils/observe.py).",
)
_define(
    "SLOW_QUERY_MS", "float", 1000.0,
    "Slow-operation threshold in milliseconds: queries/commits slower "
    "than this are force-sampled (their buffered spans exported even "
    "when the trace was unsampled) and appended to the slow-query log "
    "(utils/observe.maybe_log_slow).",
)
_define(
    "STORAGE", "str", "mem",
    "Default KV backend: 'mem' (WAL-backed in-memory) or 'lsm' "
    "(spill-to-disk SSTables) (storage/kv.py).",
)
_define(
    "STREAM_ENCODER", "bool", True,
    "Streaming arena result encoder (query/streamjson.py): response "
    "JSON streams straight from the ragged (flat_uids, offsets) level "
    "buffers into byte buffers, with native block-at-a-time emission "
    "of hex-uid and count-object arrays — byte-identical to the dict "
    "encoder by contract. 0 is the escape hatch back to the "
    "ExecNode->dict->json.dumps path (query/outputjson.py) for A/B "
    "benchmarking (BENCH_ENCODE.json) and triage.",
)
_define(
    "TABLET_TRAFFIC", "bool", True,
    "Per-tablet traffic accounting (utils/observe.py TabletTraffic): "
    "every level read and committed mutation records into a sharded "
    "(namespace, predicate) accumulator served at /debug/tablets and "
    "consumed by the traffic-driven rebalancer. Always-on by design "
    "(overhead proven within noise in BENCH_OBS.json); 0 is the A/B "
    "escape hatch for that capture.",
)
_define(
    "TRACE", "bool", True,
    "Master tracing switch. 0 = spans become allocation-only no-ops "
    "(no ids, no ring, no histograms) — the benchmarking baseline for "
    "BENCH_OBS.json (utils/observe.py).",
)
_define(
    "TRACE_SAMPLE", "float", 1.0,
    "Trace sampling ratio decided at the root span and propagated in "
    "the wire context (W3C traceparent flags). Unsampled spans still "
    "feed the in-process ring, the per-trace buffer, and latency "
    "histograms; only JSONL/OTLP export is skipped. Slow queries are "
    "force-sampled regardless (utils/observe.py).",
)
_define(
    "TRACE_SINK", "str", "",
    "DIRECTORY for per-process span JSONL sinks: each alpha/zero/"
    "coordinator process writes spans-<instance>.jsonl inside it "
    "(utils/observe.init_from_env). Inherited by spawned replicas.",
)
_define(
    "VEC_COALESCE", "bool", True,
    "Coalesce concurrent plain (unfiltered) similar_to tasks from "
    "different in-flight queries into ONE vector search_batch dispatch "
    "through the serving micro-batcher (query/functions.py + serving/"
    "microbatch.py read_similar). Only active when the batcher itself "
    "is on (DGRAPH_TPU_BATCH_WINDOW_US > 0); results are byte-identical "
    "to solo execution by construction (rows are scored independently).",
)
_define(
    "VEC_NLIST", "int", 0,
    "IVF cell count for vector indexes without an explicit constructor "
    "value; 0 = auto (2*sqrt(n), the FAISS rule of thumb) "
    "(models/vector.py).",
)
_define(
    "VEC_NPROBE", "int", 0,
    "IVF cells probed per vector search for indexes without an explicit "
    "constructor value; 0 = auto (nlist/128, floor 8, on the quantized "
    "engine — top-2 cell multi-assignment already doubles coverage and "
    "serve cost scales ~linearly with the probed pool — and nlist/32, "
    "floor 8, on the jitted float path) (models/vector.py).",
)
_define(
    "VEC_QUANT", "bool", True,
    "Scalar-quantized vector engine: corpus stored as per-row int8 with "
    "scale/offset sidecars, scored by the native qint8 kernels "
    "(codec.cpp vec_qi8_topk*) with a float32 rerank of the surviving "
    "pool (models/vector.py). Applies on CPU-backend hosts above the "
    "small-corpus cutoff; 0 is the A/B escape hatch back to the jitted "
    "float32 paths (BENCH_VECTOR.json).",
)
_define(
    "VEC_REBUILD_IMBALANCE", "float", 4.0,
    "Deferred-repartition trigger for the incremental quantized IVF: "
    "repartition when the max/avg cell ratio GROWS past this multiple "
    "of its post-build baseline (mutation skew — centroids retrained "
    "on a sample, since the old ones would reproduce the same hot "
    "cells), or when tombstoned entries exceed a quarter of the live "
    "corpus (cells reassigned, centroids kept). Mutations themselves "
    "never trigger inline work — inserts append to their nearest "
    "cells, removes tombstone in place (models/vector.py).",
)
_define(
    "VEC_RERANK", "int", 4,
    "Float32 rerank pool as a multiple of k for quantized vector "
    "searches: the qint8 scan keeps rerank*k candidates, which are "
    "re-scored exactly against the float corpus so quantization error "
    "cannot reorder the final top-k (models/vector.py).",
)
_define(
    "VEC_THREADS", "int", 0,
    "Worker threads for the threaded native quantized-vector kernels "
    "(batched candidate-list scan vec_qi8_topk_lists, corpus "
    "quantization vec_qi8_quantize, and the int8 top-2 cell "
    "assignment); 0 = auto, one per core (models/vector.py).",
)
_define(
    "WIRE_COMPRESS", "bool", False,
    "zlib-compress bulk wire blobs; default OFF because zlib-1 is "
    "slower than LAN/ICI-class links — enable for DCN-class links "
    "(conn/frame.py, FRAMING_BENCH.json).",
)


# ---------------------------------------------------------------------------
# accessors
# ---------------------------------------------------------------------------


def knob(name: str) -> Knob:
    return REGISTRY[name]


def get_raw(name: str) -> Optional[str]:
    """The raw env string for a registered knob, or None when unset."""
    return os.environ.get(REGISTRY[name].env)


def _env_reader():
    """Fast live env lookup: os.environ.get pays Mapping dispatch plus
    key encode on every call, which adds up on knobs polled per commit
    or per query (the write hot path reads ~10 knobs per txn). The
    underlying os.environ._data dict sees every write made through
    os.environ (set_env, monkeypatch.setenv, direct assignment), so a
    plain dict.get against it keeps read-live-per-call semantics.
    Falls back to os.environ.get when _data is missing or keyed
    differently (non-CPython, Windows)."""
    data = getattr(os.environ, "_data", None)
    if isinstance(data, dict):
        probe = PREFIX + "__PROBE__"
        os.environ[probe] = "1"
        try:
            pb = probe.encode()
            if pb in data:
                dget = data.get

                def read(env: str):
                    raw = dget(env.encode())
                    return raw if raw is None else raw.decode()

                return read
            if probe in data:
                return data.get
        finally:
            del os.environ[probe]
    return os.environ.get


_env_read = _env_reader()
# per-knob (raw, parsed) memo: env reads stay live; only the parse of
# an unchanged raw string is skipped
_parse_memo: Dict[str, tuple] = {}


def get(name: str) -> Any:
    """Parsed value of a registered knob; the declared default when the
    variable is unset or malformed. Reads the environment live on every
    call (tests flip env vars mid-process and expect immediate effect)."""
    k = REGISTRY[name]
    raw = _env_read(k.env)
    if raw is None:
        return k.default
    memo = _parse_memo.get(name)
    if memo is not None and memo[0] == raw:
        return memo[1]
    try:
        val = k.parse(raw)
    except ValueError:
        val = k.default
    _parse_memo[name] = (raw, val)
    return val


def set_env(name: str, value: Any) -> None:
    """Write a knob into the process environment (inherited by spawned
    replicas) — the sanctioned alternative to a raw os.environ write."""
    k = REGISTRY[name]
    if k.type == "bool":
        raw = "1" if value else "0"
    else:
        raw = str(value)
    os.environ[k.env] = raw


def unset_env(name: str) -> None:
    os.environ.pop(REGISTRY[name].env, None)


def is_set(name: str) -> bool:
    return REGISTRY[name].env in os.environ


def resolved() -> Dict[str, Any]:
    """{knob: {env, value, set}} for every registered knob — the
    effective configuration as the process sees it right now. Served at
    /debug/config and captured into debug bundles, so "what was this
    knob during the incident" is answerable from recorded evidence."""
    return {
        name: {
            "env": REGISTRY[name].env,
            "value": get(name),
            "set": is_set(name),
        }
        for name in sorted(REGISTRY)
    }


# ---------------------------------------------------------------------------
# documentation
# ---------------------------------------------------------------------------


def _default_repr(k: Knob) -> str:
    if k.default is None:
        return "_(unset)_"
    if k.type == "bool":
        return "`1`" if k.default else "`0`"
    if k.type == "str":
        return f"`{k.default}`" if k.default else "_(empty)_"
    if k.type == "int" and k.default >= 1 << 16:
        # big byte/size constants read better as shifted forms
        v = int(k.default)
        if v and (v & (v - 1)) == 0:
            return f"`{v}` (1<<{v.bit_length() - 1})"
    return f"`{k.default}`"


def reference_table() -> str:
    """The CONFIG.md body: one Markdown table row per registered knob."""
    lines = [
        "# CONFIG — `DGRAPH_TPU_*` environment reference",
        "",
        "Generated from `dgraph_tpu/x/config.py` "
        "(`python -m dgraph_tpu.cli config-ref`); a tier-1 test asserts "
        "this file matches the registry. Booleans accept "
        "`1/true/yes/on` and `0/false/no/off`; malformed values fall "
        "back to the default instead of crashing.",
        "",
        "| Variable | Type | Default | Description |",
        "|---|---|---|---|",
    ]
    for name in sorted(REGISTRY):
        k = REGISTRY[name]
        doc = " ".join(k.doc.split())
        lines.append(
            f"| `{k.env}` | {k.type} | {_default_repr(k)} | {doc} |"
        )
    lines.append("")
    return "\n".join(lines)

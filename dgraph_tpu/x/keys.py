"""Badger-style key layout for the host posting store.

Mirrors the semantics of /root/reference/x/keys.go (DataKey:201,
IndexKey:258, ReverseKey:223, CountKey:279, SchemaKey:174, TypeKey:186):
keys order by (namespace|attr) prefix first so a whole predicate (tablet) is
one contiguous range — that contiguity is what makes predicate-level
sharding, moves, and prefix iteration work.

Layout (bytes, big-endian so lexicographic order == numeric order):
  [tag:1][len(nsattr):2][nsattr][kind:1][suffix]
    tag:    0x00 data/index/reverse/count, 0x01 schema, 0x02 type
    nsattr: 8-byte namespace (big-endian u64) + attr utf-8
            (ref x/keys.go NamespaceAttr — namespace is baked into the attr)
    kind:   0x00 data(uid u64) | 0x02 index(term bytes) | 0x04 reverse(uid)
            | 0x08 count(u32 count + rev flag)
Split keys (multi-part posting lists, ref x/keys.go:42 ByteSplit) append a
part id; handled by posting/ when lists exceed the split threshold.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Optional

TAG_DEFAULT = 0x00
TAG_SCHEMA = 0x01
TAG_TYPE = 0x02
TAG_SPLIT = 0x03  # multi-part posting-list part (ref x/keys.go:512 SplitKey)

KIND_DATA = 0x00
KIND_INDEX = 0x02
KIND_REVERSE = 0x04
KIND_COUNT = 0x08

GALAXY_NS = 0  # default namespace (ref x/keys.go GalaxyNamespace)


def namespace_attr(ns: int, attr: str) -> bytes:
    return struct.pack(">Q", ns) + attr.encode("utf-8")


def attr_from_nsattr(nsattr: bytes) -> tuple[int, str]:
    ns = struct.unpack(">Q", nsattr[:8])[0]
    return ns, nsattr[8:].decode("utf-8")


def _prefix(tag: int, nsattr: bytes) -> bytes:
    return struct.pack(">BH", tag, len(nsattr)) + nsattr


# key-kind prefix cache: the (tag, ns, attr, kind) head of a key is
# attr-constant, and the mutation path builds several keys per edge —
# re-packing the prefix each time was measurable on the live write
# path. Bounded by a wholesale clear (attrs are few; a clear only
# costs re-derivation).
_PFX_CACHE: dict = {}


def _kind_prefix(kind: int, attr: str, ns: int) -> bytes:
    ck = (kind, attr, ns)
    p = _PFX_CACHE.get(ck)
    if p is None:
        if len(_PFX_CACHE) > 8192:
            _PFX_CACHE.clear()
        p = _PFX_CACHE[ck] = (
            _prefix(TAG_DEFAULT, namespace_attr(ns, attr)) + bytes([kind])
        )
    return p


def DataKey(attr: str, uid: int, ns: int = GALAXY_NS) -> bytes:
    return _kind_prefix(KIND_DATA, attr, ns) + struct.pack(">Q", uid)


def ReverseKey(attr: str, uid: int, ns: int = GALAXY_NS) -> bytes:
    return _kind_prefix(KIND_REVERSE, attr, ns) + struct.pack(">Q", uid)


def IndexKey(attr: str, term: bytes, ns: int = GALAXY_NS) -> bytes:
    if isinstance(term, str):
        term = term.encode("utf-8")
    return _kind_prefix(KIND_INDEX, attr, ns) + term


def CountKey(attr: str, count: int, reverse: bool = False, ns: int = GALAXY_NS) -> bytes:
    return (
        _kind_prefix(KIND_COUNT, attr, ns)
        + struct.pack(">I", count)
        + (b"\x01" if reverse else b"\x00")
    )


def SchemaKey(attr: str, ns: int = GALAXY_NS) -> bytes:
    return _prefix(TAG_SCHEMA, namespace_attr(ns, attr))


def TypeKey(name: str, ns: int = GALAXY_NS) -> bytes:
    return _prefix(TAG_TYPE, namespace_attr(ns, name))


def PredicatePrefix(attr: str, ns: int = GALAXY_NS) -> bytes:
    """Prefix covering all data/index/reverse/count keys of one predicate."""
    return _prefix(TAG_DEFAULT, namespace_attr(ns, attr))


def DataPrefix(attr: str, ns: int = GALAXY_NS) -> bytes:
    return PredicatePrefix(attr, ns) + bytes([KIND_DATA])


def IndexPrefix(attr: str, ns: int = GALAXY_NS) -> bytes:
    return PredicatePrefix(attr, ns) + bytes([KIND_INDEX])


def ReversePrefix(attr: str, ns: int = GALAXY_NS) -> bytes:
    return PredicatePrefix(attr, ns) + bytes([KIND_REVERSE])


def CountPrefix(attr: str, ns: int = GALAXY_NS) -> bytes:
    return PredicatePrefix(attr, ns) + bytes([KIND_COUNT])


def SplitKey(base_key: bytes, start_uid: int) -> bytes:
    """Part key of a multi-part posting list: the base (data/index/reverse)
    key re-tagged into the split region + the part's first uid
    (ref x/keys.go:512 SplitKey — same idea, separate key region so data
    prefix iteration never sees parts)."""
    if base_key[0] != TAG_DEFAULT:
        raise ValueError("only default-region keys can be split")
    return bytes([TAG_SPLIT]) + base_key[1:] + struct.pack(">Q", start_uid)


def base_of_split(split_key: bytes) -> tuple[bytes, int]:
    """Inverse of SplitKey: (base_key, start_uid)."""
    if split_key[0] != TAG_SPLIT:
        raise ValueError("not a split key")
    start = struct.unpack(">Q", split_key[-8:])[0]
    return bytes([TAG_DEFAULT]) + split_key[1:-8], start


def SplitPredicatePrefix(attr: str, ns: int = GALAXY_NS) -> bytes:
    """Prefix covering every part key of one predicate (for drops/moves)."""
    return bytes([TAG_SPLIT]) + PredicatePrefix(attr, ns)[1:]


@dataclass
class ParsedKey:
    """Decoded key (ref x/keys.go:330 ParsedKey)."""

    tag: int
    ns: int
    attr: str
    kind: Optional[int] = None
    uid: Optional[int] = None
    term: Optional[bytes] = None
    count: Optional[int] = None
    count_reverse: bool = False
    split_start: Optional[int] = None  # set for TAG_SPLIT part keys

    @property
    def is_data(self):
        return self.tag == TAG_DEFAULT and self.kind == KIND_DATA

    @property
    def is_index(self):
        return self.tag == TAG_DEFAULT and self.kind == KIND_INDEX

    @property
    def is_reverse(self):
        return self.tag == TAG_DEFAULT and self.kind == KIND_REVERSE

    @property
    def is_count(self):
        return self.tag == TAG_DEFAULT and self.kind == KIND_COUNT

    @property
    def is_schema(self):
        return self.tag == TAG_SCHEMA

    @property
    def is_type(self):
        return self.tag == TAG_TYPE


def attr_of(key_or_prefix: bytes) -> Optional[str]:
    """Extract the attr from a key OR a bare prefix (which lacks the
    kind/uid suffix a full parse_key needs)."""
    if len(key_or_prefix) < 3:
        return None
    tag, nlen = struct.unpack_from(">BH", key_or_prefix, 0)
    if len(key_or_prefix) < 3 + nlen:
        return None
    _, attr = attr_from_nsattr(key_or_prefix[3 : 3 + nlen])
    return attr


def parse_key(key: bytes) -> ParsedKey:
    tag, nlen = struct.unpack_from(">BH", key, 0)
    nsattr = key[3 : 3 + nlen]
    ns, attr = attr_from_nsattr(nsattr)
    rest = key[3 + nlen :]
    if tag in (TAG_SCHEMA, TAG_TYPE):
        return ParsedKey(tag=tag, ns=ns, attr=attr)
    if tag == TAG_SPLIT:
        base, start = base_of_split(key)
        pk = parse_key(base)
        pk.tag = TAG_SPLIT
        pk.split_start = start
        return pk
    kind = rest[0]
    body = rest[1:]
    pk = ParsedKey(tag=tag, ns=ns, attr=attr, kind=kind)
    if kind in (KIND_DATA, KIND_REVERSE):
        pk.uid = struct.unpack(">Q", body)[0]
    elif kind == KIND_INDEX:
        pk.term = body
    elif kind == KIND_COUNT:
        pk.count = struct.unpack(">I", body[:4])[0]
        pk.count_reverse = body[4:5] == b"\x01"
    return pk

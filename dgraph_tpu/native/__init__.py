"""Native host kernel loader: compiles codec.cpp once, binds via ctypes.

The C++ layer covers the host-side hot paths (SURVEY.md §2.7): the
bit-pack codec used by UID pack (de)serialization and the scalar sorted-set
ops used by the dispatcher's small-op fallback. Python/numpy fallbacks keep
everything working where no compiler exists (`NATIVE_AVAILABLE` tells you
which you got).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Optional

import numpy as np

from dgraph_tpu.x import config

_LIB: Optional[ctypes.CDLL] = None
NATIVE_AVAILABLE = False

# ---------------------------------------------------------------------------
# ctypes ABI declarations
#
# ONE declarative table, consumed by BOTH the binder below and the static
# ABI cross-checker (dgraph_tpu/analysis/check_ctypes_abi.py), which parses
# the extern "C" signatures in codec.cpp / bulkload.cpp and verifies arity,
# widths and signedness against this table. Every exported function must be
# listed with an EXPLICIT restype: a missing restype on an int64_t-returning
# function silently truncates through ctypes' c_int default — on results
# >= 2**31 (flat decode counts, file offsets) that is a memory-corruption
# class bug, not a style nit. restype None == C void.
# ---------------------------------------------------------------------------

_u8p = ctypes.POINTER(ctypes.c_uint8)
_u32p = ctypes.POINTER(ctypes.c_uint32)
_u64p = ctypes.POINTER(ctypes.c_uint64)
_i8p = ctypes.POINTER(ctypes.c_int8)
_i32 = ctypes.c_int32
_i32p = ctypes.POINTER(ctypes.c_int32)
_i64 = ctypes.c_int64
_i64p = ctypes.POINTER(ctypes.c_int64)
_u64 = ctypes.c_uint64
_int = ctypes.c_int
_f32 = ctypes.c_float
_f32p = ctypes.POINTER(ctypes.c_float)
_vp = ctypes.c_void_p
_cp = ctypes.c_char_p

DECLS = {
    # codec.cpp — bit-pack codec + sorted-set kernels
    "bitpack": (None, [_u32p, _i64, _int, _u8p]),
    "bitunpack": (None, [_u8p, _i64, _i64, _int, _u32p]),
    "pack_decode_blocks": (_i64, [_u64p, _i32p, _u32p, _i64, _i64p, _i64, _u64p]),
    "packs_decode_many": (
        _i64,
        [
            ctypes.POINTER(_u64p), ctypes.POINTER(_i32p),
            ctypes.POINTER(_u32p), _i64p, _i64, _i64, _u64p, _i64p,
        ],
    ),
    "pack_intersect_small": (
        _i64,
        [_u64p, _i32p, _u32p, _i64, _i64, _u64p, _u64p, _i64, _u64p, _i64p],
    ),
    # codec.cpp — adaptive bitmap/packed block engine
    "pack_build_bitmaps": (
        None,
        [_i32p, _u32p, _i64, _i64, _i32p, _i64, _u64p],
    ),
    "pack_pair_setop": (
        _i64,
        [
            _int,
            _u64p, _i32p, _u32p, _i64, _i64, _u64p, _u64p, _i32p,
            _u64p, _i32p, _u32p, _i64, _i64, _u64p, _u64p, _i32p,
            _i64, _u64p, _i64p,
        ],
    ),
    "pack_stream_setop": (
        _i64,
        [
            _int, _u64p, _i64,
            _u64p, _i32p, _u32p, _i64, _i64, _u64p, _u64p, _i32p,
            _i64, _u64p, _i64p,
        ],
    ),
    # codec.cpp — streaming arena result encoder
    "enc_uid_objs": (_i64, [_u64p, _i64, _u8p, _i64, _u8p, _i64, _u8p]),
    "enc_int_objs": (_i64, [_i64p, _i64, _u8p, _i64, _u8p, _i64, _u8p]),
    # codec.cpp — mutation write-path kernels (group commit)
    "enc_delta_records": (
        _i64,
        [_i64p, _i64, _u8p, _u64p, _u8p, _i64p, _u8p, _u8p, _i64p],
    ),
    "tok_terms_ascii": (
        _i64,
        [_u8p, _i64p, _i64, _int, _u8p, _i64p, _i64p],
    ),
    # codec.cpp — columnar batch apply (posting/colwrite.py). void*
    # params by design: the wrapper passes raw buffer addresses
    # (array.array buffer_info / bytes), skipping the per-argument
    # ctypes pointer casts that dominate small-batch commit cost
    "batch_apply": (
        _i64,
        [
            _vp, _i64, _vp, _vp, _vp, _vp, _vp, _vp, _vp,
            _vp, _vp, _vp, _vp, _i64,
            _vp, _vp, _vp, _vp, _vp, _vp, _vp, _vp, _i64,
        ],
    ),
    "batch_apply_caps": (
        _i64,
        [_vp, _i64, _vp, _vp, _vp, _vp, _vp, _i64, _vp],
    ),
    # codec.cpp — quantized vector scoring (models/vector.py)
    "vec_qi8_topk": (
        _i64,
        [
            _i8p, _i64, _i64, _f32p, _f32p, _i32p, _f32p, _u8p,
            _i8p, _f32p, _f32p, _i32p, _f32p,
            _i64, _int, _i64, _i64p, _f32p,
        ],
    ),
    "vec_qi8_topk_idx": (
        _i64,
        [
            _i8p, _i64, _f32p, _f32p, _i32p, _f32p, _u8p,
            _i32p, _i64, _i8p, _f32, _f32, _i32, _f32,
            _int, _i64, _i64p, _f32p,
        ],
    ),
    "vec_qi8_topk_lists": (
        _i64,
        [
            _i8p, _i64, _f32p, _f32p, _i32p, _f32p, _u8p,
            _i32p, _i64p, _i64p,
            _i8p, _f32p, _f32p, _i32p, _f32p,
            _i64, _int, _i64, _i64, _i64p, _f32p,
        ],
    ),
    "vec_qi8_quantize": (
        _i64,
        [_f32p, _i64, _i64, _i64, _i8p, _f32p, _f32p, _i32p, _f32p],
    ),
    "intersect_u64": (_i64, [_u64p, _i64, _u64p, _i64, _u64p]),
    "union_u64": (_i64, [_u64p, _i64, _u64p, _i64, _u64p]),
    "difference_u64": (_i64, [_u64p, _i64, _u64p, _i64, _u64p]),
    "merge_sorted_u64": (_i64, [_u64p, _i64p, _i64, _u64p, _u64p]),
    # codec.cpp — SSTable entry scans
    "sst_seek": (_i64, [_u8p, _i64, _i64, _u8p, _i64]),
    "sst_versions": (
        _i64,
        [_u8p, _i64, _i64, _u8p, _i64, _i64, _u64p, _u64p, _i64p, _i64p],
    ),
    "sst_versions_multi": (
        _i64,
        [
            _u8p, _i64, _i64, _u8p, _i64p, _i64p, _i64p, _i64,
            _i64p, _u64p, _u64p, _i64p, _i64p,
        ],
    ),
    "sst_scan": (
        _i64,
        [
            _u8p, _i64, _i64, _u8p, _i64, _i64,
            _i64p, _i64p, _u64p, _u64p, _i64p, _i64p, _i64p,
        ],
    ),
    # bulkload.cpp — offline bulk-load pipeline
    "bulk_new": (_vp, []),
    "bulk_free": (None, [_vp]),
    "bulk_scan_xids": (_i64, [_vp, _cp, _i64]),
    "bulk_set_base": (None, [_vp, _u64]),
    "bulk_xid_lookup": (_u64, [_vp, _cp, _i64]),
    "bulk_clear_preds": (None, [_vp]),
    "bulk_add_pred": (_int, [_vp, _cp, _i64, _int, _int, _u8p, _i64, _u64]),
    "bulk_map": (_i64, [_vp, _cp, _i64, _u64, _cp, _cp, _i64]),
    "bulk_run_count": (_i64, [_vp]),
    "bulk_run_path": (_i64, [_vp, _i64, _cp, _i64]),
    "bulk_reduce": (
        _i64,
        [_vp, _cp, _i64, _u64, _cp, _cp, _cp, _u64, _i64, _u64, _u64],
    ),
}

# sanitizer build modes: flags + a cache-key suffix so instrumented and
# plain builds never collide in the shared /tmp cache dir
_SAN_FLAGS = {
    "": [],
    # UBSan aborts on the first finding (no silent recovery) — the
    # randomized packed-setops corpus runs under this in the slow suite
    "ubsan": ["-fsanitize=undefined", "-fno-sanitize-recover=all"],
    # ASan .so needs the asan runtime loaded FIRST: run python under
    # LD_PRELOAD=$(g++ -print-file-name=libasan.so) (see README)
    "asan": ["-fsanitize=address"],
    # TSan is the only tool that sees races inside the std::thread
    # fan-outs (vec_qi8_topk_lists, vec_qi8_quantize, batch_apply
    # under concurrent group-commit batches); same LD_PRELOAD story
    # with libtsan.so — tests/test_native_san.py drives the matrix
    "tsan": ["-fsanitize=thread"],
}


def _build_and_load() -> Optional[ctypes.CDLL]:
    here = os.path.dirname(__file__)
    srcs = [
        os.path.join(here, "codec.cpp"),
        os.path.join(here, "bulkload.cpp"),
    ]
    h = hashlib.sha256()
    for s in srcs:
        with open(s, "rb") as f:
            h.update(f.read())
    tag = h.hexdigest()[:16]
    san = config.get("NATIVE_SAN").strip().lower()
    san_flags = _SAN_FLAGS.get(san)
    if san_flags is None:
        return None  # unknown sanitizer name: fail to python, don't guess
    if san:
        tag = f"{tag}-{san}"
    cache_dir = config.get("NATIVE_CACHE") or os.path.join(
        tempfile.gettempdir(), "dgraph_tpu_native"
    )
    os.makedirs(cache_dir, exist_ok=True)
    so_path = os.path.join(cache_dir, f"codec-{tag}.so")
    if not os.path.exists(so_path):
        tmp = so_path + f".tmp{os.getpid()}"
        cmd = [
            "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
            *san_flags, "-o", tmp, *srcs,
        ]
        # -march=native unlocks SIMD; retry without it if unsupported
        try:
            subprocess.run(
                cmd[:2] + ["-march=native"] + cmd[2:],
                check=True, capture_output=True, timeout=120,
            )
        except (subprocess.CalledProcessError, FileNotFoundError,
                subprocess.TimeoutExpired):
            try:
                subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            except Exception:
                return None
        os.replace(tmp, so_path)
    try:
        lib = ctypes.CDLL(so_path)
    except OSError:
        return None
    for name, (restype, argtypes) in DECLS.items():
        fn = getattr(lib, name)
        fn.restype = restype
        fn.argtypes = argtypes
    return lib


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


try:
    _LIB = _build_and_load()
    NATIVE_AVAILABLE = _LIB is not None
except Exception:
    _LIB = None
    NATIVE_AVAILABLE = False


# ---------------------------------------------------------------------------
# numpy-facing wrappers (with pure-Python fallbacks)
# ---------------------------------------------------------------------------


def bitpack(vals: np.ndarray, width: int) -> bytes:
    vals = np.ascontiguousarray(vals, dtype=np.uint32)
    n = vals.size
    if width == 0 or n == 0:
        return b""
    nbytes = (n * width + 7) // 8
    if n <= 32:
        # tiny arrays: ctypes marshaling costs more than packing — build
        # one big int and slice its bytes (bulk loads are dominated by
        # small per-key lists)
        acc = 0
        shift = 0
        for v in vals.tolist():
            acc |= int(v) << shift
            shift += width
        return acc.to_bytes(nbytes, "little")
    if _LIB is not None:
        out = np.zeros((nbytes + 8,), np.uint8)  # slack for the 5-byte write
        _LIB.bitpack(
            _ptr(vals, ctypes.c_uint32), n, width, _ptr(out, ctypes.c_uint8)
        )
        return out[:nbytes].tobytes()
    from dgraph_tpu.codec.uidpack import _bitpack_py

    return _bitpack_py(vals, width)


def bitunpack(data: bytes, count: int, width: int) -> np.ndarray:
    if width == 0 or count == 0:
        return np.zeros((count,), np.uint32)
    if count <= 32:
        acc = int.from_bytes(data[: (count * width + 7) // 8], "little")
        mask = (1 << width) - 1
        return np.fromiter(
            ((acc >> (i * width)) & mask for i in range(count)),
            dtype=np.uint32,
            count=count,
        )
    if _LIB is not None:
        buf = np.frombuffer(data, dtype=np.uint8)
        out = np.empty((count,), np.uint32)
        _LIB.bitunpack(
            _ptr(buf, ctypes.c_uint8),
            buf.size,
            count,
            width,
            _ptr(out, ctypes.c_uint32),
        )
        return out
    from dgraph_tpu.codec.uidpack import _bitunpack_py

    return _bitunpack_py(data, count, width)


def pack_decode_blocks(bases, counts, offsets, idxs):
    """Partial UidPack decode (codec/uidpack.decode_blocks fast path).
    Returns the decoded sorted u64 array, or None when the native lib is
    unavailable (caller falls back to the numpy masked broadcast)."""
    if _LIB is None:
        return None
    idxs = np.ascontiguousarray(idxs, np.int64)
    total = int(counts[idxs].sum())
    out = np.empty((total,), np.uint64)
    if total == 0:
        return out
    # bind conversions to locals so any converted temporaries outlive the
    # native call (inline _ptr(ascontiguousarray(...)) would free them
    # before the call runs)
    bases = np.ascontiguousarray(bases, np.uint64)
    counts = np.ascontiguousarray(counts, np.int32)
    offsets = np.ascontiguousarray(offsets, np.uint32)
    n = _LIB.pack_decode_blocks(
        _ptr(bases, ctypes.c_uint64),
        _ptr(counts, ctypes.c_int32),
        _ptr(offsets, ctypes.c_uint32),
        offsets.shape[1],
        idxs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        idxs.size,
        _ptr(out, ctypes.c_uint64),
    )
    return out[:n]


def packs_decode_many(packs):
    """Decode N UidPacks into (flat u64 buffer, int64[n+1] prefix offsets)
    in ONE native call — the level-batched fan-out read path (N parents'
    posting lists materialized together). Returns None when the native lib
    is unavailable (caller falls back to per-pack decode)."""
    if _LIB is None:
        return None
    n = len(packs)
    offs = np.zeros((n + 1,), np.int64)
    total = sum(p.num_uids for p in packs)
    out = np.empty((total,), np.uint64)
    if n == 0 or total == 0:
        return out, offs
    u64p = ctypes.POINTER(ctypes.c_uint64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    bases_pp = (u64p * n)()
    counts_pp = (i32p * n)()
    offsets_pp = (u32p * n)()
    nblocks = np.empty((n,), np.int64)
    block_size = 0
    # keep converted temporaries alive past the native call
    keep = []
    for i, p in enumerate(packs):
        b = np.ascontiguousarray(p.bases, np.uint64)
        c = np.ascontiguousarray(p.counts, np.int32)
        o = np.ascontiguousarray(p.offsets, np.uint32)
        keep.append((b, c, o))
        bases_pp[i] = _ptr(b, ctypes.c_uint64)
        counts_pp[i] = _ptr(c, ctypes.c_int32)
        offsets_pp[i] = _ptr(o, ctypes.c_uint32)
        nblocks[i] = b.size
        if o.ndim == 2 and o.shape[1]:
            block_size = o.shape[1]
    _LIB.packs_decode_many(
        bases_pp,
        counts_pp,
        offsets_pp,
        nblocks.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        block_size,
        n,
        _ptr(out, ctypes.c_uint64),
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return out, offs


def pack_ptrs(bases, counts, offsets, maxes):
    """Pre-built ctypes pointers for a long-lived pack's block arrays —
    callers cache the tuple on the pack so per-op calls skip the
    numpy->ctypes marshaling that dominates tiny-frontier latency (same
    trick as buf_ptr for SSTable mmaps)."""
    return (
        _ptr(bases, ctypes.c_uint64),
        _ptr(counts, ctypes.c_int32),
        _ptr(offsets, ctypes.c_uint32),
        _ptr(maxes, ctypes.c_uint64),
    )


def pack_intersect_small(bases, counts, offsets, maxes, a, ptrs=None):
    """Tiny-frontier compressed-domain intersect: one native call, zero
    decode. Returns (hits u64 array, touched_uids) or None when the native
    lib is unavailable."""
    if _LIB is None:
        return None
    if ptrs is None:
        ptrs = pack_ptrs(bases, counts, offsets, maxes)
    a = np.ascontiguousarray(a, np.uint64)
    out = np.empty((a.size,), np.uint64)
    touched = ctypes.c_int64(0)
    n = _LIB.pack_intersect_small(
        ptrs[0],
        ptrs[1],
        ptrs[2],
        offsets.shape[1],
        bases.size,
        ptrs[3],
        _ptr(a, ctypes.c_uint64),
        a.size,
        _ptr(out, ctypes.c_uint64),
        ctypes.byref(touched),
    )
    return out[:n], int(touched.value)


def pack_build_bitmaps(counts, offsets, rows, bm_bits, out_words) -> bool:
    """Scatter eligible blocks' offsets into the zeroed COMPACT bitset
    matrix; `rows` maps block index -> words row (or -1)
    (codec/uidpack.block_bitmaps fast path). Returns False when the
    native lib is unavailable (caller falls back to the numpy scatter)."""
    if _LIB is None:
        return False
    counts = np.ascontiguousarray(counts, np.int32)
    offsets = np.ascontiguousarray(offsets, np.uint32)
    rows = np.ascontiguousarray(rows, np.int32)
    _LIB.pack_build_bitmaps(
        _ptr(counts, ctypes.c_int32),
        _ptr(offsets, ctypes.c_uint32),
        offsets.shape[1],
        counts.size,
        _ptr(rows, ctypes.c_int32),
        bm_bits,
        _ptr(out_words, ctypes.c_uint64),
    )
    return True


def _bm_arrays(words, rows, ok):
    """(words, rows) contiguous arrays for a compact bitmap sidecar, or
    (None, None) when no block is eligible (the kernels take the packed
    arms only). Callers MUST bind the returns to locals so the converted
    temporaries outlive the native call."""
    if words is None:
        return None, None
    return (
        np.ascontiguousarray(words, np.uint64),
        np.ascontiguousarray(rows, np.int32),
    )


def pack_pair_setop(op, pa, pb, a_bm, b_bm, bm_bits):
    """Compressed-domain pack x pack set op (0=intersect, 1=difference)
    via the adaptive per-block-pair engine. `a_bm`/`b_bm` are the
    compact (words, rows, ok) bitmap sidecars from
    codec/uidpack.block_bitmaps.
    Returns (result u64 array, kernel_counts int64[4]) or None when the
    native lib is unavailable."""
    if _LIB is None:
        return None
    cap = min(pa.num_uids, pb.num_uids) if op == 0 else pa.num_uids
    out = np.empty((cap,), np.uint64)
    kc = np.zeros((4,), np.int64)
    if cap == 0:
        return out, kc
    a_b = np.ascontiguousarray(pa.bases, np.uint64)
    a_c = np.ascontiguousarray(pa.counts, np.int32)
    a_o = np.ascontiguousarray(pa.offsets, np.uint32)
    b_b = np.ascontiguousarray(pb.bases, np.uint64)
    b_c = np.ascontiguousarray(pb.counts, np.int32)
    b_o = np.ascontiguousarray(pb.offsets, np.uint32)
    # keep sidecar conversions alive past the call
    a_wa, a_ra = _bm_arrays(*a_bm)
    b_wa, b_ra = _bm_arrays(*b_bm)
    a_words = _ptr(a_wa, ctypes.c_uint64) if a_wa is not None else None
    a_rowsp = _ptr(a_ra, ctypes.c_int32) if a_ra is not None else None
    b_words = _ptr(b_wa, ctypes.c_uint64) if b_wa is not None else None
    b_rowsp = _ptr(b_ra, ctypes.c_int32) if b_ra is not None else None
    from dgraph_tpu.codec.uidpack import block_maxes

    a_m = block_maxes(pa)
    b_m = block_maxes(pb)
    n = _LIB.pack_pair_setop(
        op,
        _ptr(a_b, ctypes.c_uint64), _ptr(a_c, ctypes.c_int32),
        _ptr(a_o, ctypes.c_uint32), a_o.shape[1], a_b.size,
        _ptr(a_m, ctypes.c_uint64), a_words, a_rowsp,
        _ptr(b_b, ctypes.c_uint64), _ptr(b_c, ctypes.c_int32),
        _ptr(b_o, ctypes.c_uint32), b_o.shape[1], b_b.size,
        _ptr(b_m, ctypes.c_uint64), b_words, b_rowsp,
        bm_bits,
        _ptr(out, ctypes.c_uint64),
        _ptr(kc, ctypes.c_int64),
    )
    return out[:n], kc


def pack_stream_setop(op, a, pack, bm, bm_bits):
    """Compressed-domain sorted-array x pack set op (0=intersect,
    1=difference): stream `a` against the pack's blocks, probing bitmap
    containers where present. Returns (result, kernel_counts int64[4])
    or None when the native lib is unavailable."""
    if _LIB is None:
        return None
    a = np.ascontiguousarray(a, np.uint64)
    out = np.empty((a.size,), np.uint64)
    kc = np.zeros((4,), np.int64)
    if a.size == 0:
        return out, kc
    bases = np.ascontiguousarray(pack.bases, np.uint64)
    counts = np.ascontiguousarray(pack.counts, np.int32)
    offsets = np.ascontiguousarray(pack.offsets, np.uint32)
    wa, ra = _bm_arrays(*bm)
    words = _ptr(wa, ctypes.c_uint64) if wa is not None else None
    rowsp = _ptr(ra, ctypes.c_int32) if ra is not None else None
    from dgraph_tpu.codec.uidpack import block_maxes

    maxes = block_maxes(pack)
    n = _LIB.pack_stream_setop(
        op,
        _ptr(a, ctypes.c_uint64), a.size,
        _ptr(bases, ctypes.c_uint64), _ptr(counts, ctypes.c_int32),
        _ptr(offsets, ctypes.c_uint32), offsets.shape[1], bases.size,
        _ptr(maxes, ctypes.c_uint64), words, rowsp,
        bm_bits,
        _ptr(out, ctypes.c_uint64),
        _ptr(kc, ctypes.c_int64),
    )
    return out[:n], kc


def _enc_objs(fn_name, vals, ctype, per_item, pre: bytes, post: bytes):
    """Shared driver for the arena encoder kernels: one native call
    emits the whole run into a fresh scratch buffer; the returned
    uint8 view is appended to the arena zero-copy (the final join is
    the only copy). Returns None when the native lib is unavailable
    (caller takes the byte-identical Python fallback)."""
    if _LIB is None:
        return None
    n = vals.size
    if n == 0:
        return np.zeros((0,), np.uint8)
    cap = n * (len(pre) + len(post) + per_item + 1)
    out = np.empty((cap,), np.uint8)
    preb = np.frombuffer(pre, np.uint8) if pre else np.zeros(1, np.uint8)
    postb = np.frombuffer(post, np.uint8) if post else np.zeros(1, np.uint8)
    got = getattr(_LIB, fn_name)(
        _ptr(vals, ctype), n,
        _ptr(preb, ctypes.c_uint8), len(pre),
        _ptr(postb, ctypes.c_uint8), len(post),
        _ptr(out, ctypes.c_uint8),
    )
    return out[:got]


def enc_uid_objs(uids: np.ndarray, pre: bytes, post: bytes):
    """`pre + hex(uid) + post` per uid, comma-joined — the
    `{"uid":"0x1"},{"uid":"0x2"}` bulk emitter (query/streamjson.py).
    Returns a uint8 array view or None without the native lib."""
    uids = np.ascontiguousarray(uids, np.uint64)
    return _enc_objs("enc_uid_objs", uids, ctypes.c_uint64, 16, pre, post)


def enc_int_objs(vals: np.ndarray, pre: bytes, post: bytes):
    """`pre + str(val) + post` per int64, comma-joined — the
    `{"c":5},{"c":3}` count-object bulk emitter."""
    vals = np.ascontiguousarray(vals, np.int64)
    return _enc_objs("enc_int_objs", vals, ctypes.c_int64, 20, pre, post)


def enc_delta_records(counts, flags, uids, tids, vlens, vblob: bytes):
    """Batched posting-delta record encode (posting/pl.encode_deltas):
    ONE native call serializes every fast-shape posting (no lang, no
    facets) of a whole txn's write set, byte-identical to the per-key
    Python encoder. Returns a list of per-key record bytes (aligned
    with `counts`), or None when the native lib is unavailable."""
    if _LIB is None:
        return None
    counts = np.ascontiguousarray(counts, np.int64)
    flags = np.ascontiguousarray(flags, np.uint8)
    uids = np.ascontiguousarray(uids, np.uint64)
    tids = np.ascontiguousarray(tids, np.uint8)
    vlens = np.ascontiguousarray(vlens, np.int64)
    n_keys = counts.size
    total = int(5 * n_keys + 17 * flags.size + vlens.sum())
    out = np.empty((total,), np.uint8)
    offs = np.empty((n_keys + 1,), np.int64)
    vb = (
        np.frombuffer(vblob, np.uint8) if vblob else np.zeros(1, np.uint8)
    )
    wrote = _LIB.enc_delta_records(
        _ptr(counts, ctypes.c_int64), n_keys,
        _ptr(flags, ctypes.c_uint8), _ptr(uids, ctypes.c_uint64),
        _ptr(tids, ctypes.c_uint8), _ptr(vlens, ctypes.c_int64),
        _ptr(vb, ctypes.c_uint8),
        _ptr(out, ctypes.c_uint8), _ptr(offs, ctypes.c_int64),
    )
    assert wrote == total, (wrote, total)
    ob = out.tobytes()
    ol = offs.tolist()  # python ints: numpy-scalar slicing is slow
    return [ob[ol[i]:ol[i + 1]] for i in range(n_keys)]


def tok_terms_ascii(values, prefix: int):
    """Bulk ASCII term tokenization (tok/tok.py TermTokenizer fast
    path): `values` is a list of pure-ASCII byte strings; returns a
    list of per-value sorted-unique token lists (each token prefixed
    with the tokenizer identifier byte), byte-identical to the Python
    tokenizer over ASCII input — or None when the native lib is
    unavailable."""
    if _LIB is None:
        return None
    n = len(values)
    blob = b"".join(values)
    offs = np.zeros((n + 1,), np.int64)
    np.cumsum(
        np.fromiter((len(v) for v in values), np.int64, n), out=offs[1:]
    )
    total = len(blob)
    max_toks = total // 2 + n + 1
    bb = np.frombuffer(blob, np.uint8) if blob else np.zeros(1, np.uint8)
    out = np.empty((total + max_toks,), np.uint8)
    tok_offs = np.empty((max_toks + 1,), np.int64)
    tok_counts = np.empty((n,), np.int64)
    ntok = _LIB.tok_terms_ascii(
        _ptr(bb, ctypes.c_uint8), _ptr(offs, ctypes.c_int64), n,
        prefix,
        _ptr(out, ctypes.c_uint8), _ptr(tok_offs, ctypes.c_int64),
        _ptr(tok_counts, ctypes.c_int64),
    )
    ob = out.tobytes()
    to = tok_offs[: ntok + 1].tolist()
    tc = tok_counts.tolist()
    result = []
    t = 0
    for i in range(n):
        cnt = tc[i]
        result.append([ob[to[j]:to[j + 1]] for j in range(t, t + cnt)])
        t += cnt
    assert t == ntok
    return result


def _ba_addr(buf) -> int:
    """Raw address of a writable buffer (bytearray) for the void*
    batch-apply params; empty buffers pass 0 (never dereferenced —
    every span over them is zero-length)."""
    if not len(buf):
        return 0
    return ctypes.addressof(ctypes.c_char.from_buffer(buf))


def batch_apply(
    m_offs, shapes, entities, pred_ids, objects, vtypes, voffs,
    vblob, pp_blob: bytes, pp_offs, pflags: bytes, pidents: bytes,
):
    """Columnar batch apply (posting/colwrite.py): ONE GIL-released
    call turns a whole group-commit batch's collected edge columns
    into ready-to-put (key, delta-record) pairs — key construction,
    exact/int/bool/term tokenization and record encoding fused.

    Columns arrive as the cheap typed buffers colwrite collects into —
    array.array('q'/'Q'/'i') for the int columns and CSR offsets,
    bytearray/bytes for the byte columns — and are passed by raw
    address (no numpy conversion, no per-arg ctypes casts: this entry
    runs once per commit batch and its Python-side fixed cost is what
    the columnar path exists to delete). Returns (n_pairs, keys_blob,
    key_offs, recs_blob, rec_offs, member, pred, kinds, counts) with
    CSR blobs as bytes and the per-pair annotations as indexable
    typed-array sequences, or None when the native lib is unavailable
    (caller materializes to the Python path)."""
    from array import array

    if _LIB is None:
        return None
    n_members = len(m_offs) - 1
    n_preds = len(pp_offs) - 1
    if n_members <= 0 or m_offs[-1] == 0:
        empty = array("q", (0,))
        return (0, b"", empty, b"", empty, b"", b"", b"", b"")
    return batch_apply_addrs(
        m_offs.buffer_info()[0], n_members,
        _ba_addr(shapes), entities.buffer_info()[0],
        pred_ids.buffer_info()[0], objects.buffer_info()[0],
        _ba_addr(vtypes), voffs.buffer_info()[0],
        _ba_addr(vblob) if isinstance(vblob, bytearray) else vblob,
        pp_blob, pp_offs.buffer_info()[0], pflags, pidents, n_preds,
    )


def batch_apply_addrs(
    a_m_offs: int, n_members: int, a_shapes: int, a_entities: int,
    a_pred_ids: int, a_objects: int, a_vtypes: int, a_voffs: int,
    a_vblob, pp_blob: bytes, a_pp_offs: int, pflags: bytes,
    pidents: bytes, n_preds: int,
):
    """Address-level core of `batch_apply`: every big input column
    arrives as a raw address, so the apply-shard worker processes
    (worker/applyshard.py) can point the kernel straight into their
    shared-memory ring — zero input copies on the worker side. Same
    return tuple as `batch_apply`; None when the lib is unavailable.
    Callers own the empty-batch short-circuit (a zero-row call here
    would dereference nothing but still pays the caps exchange)."""
    from array import array

    if _LIB is None:
        return None
    caps = array("q", (0, 0, 0))
    _LIB.batch_apply_caps(
        a_m_offs, n_members, a_shapes, a_pred_ids, a_voffs, a_pp_offs,
        pflags, n_preds, caps.buffer_info()[0],
    )
    max_pairs, key_cap, rec_cap = caps
    out_keys = bytearray(key_cap)
    out_key_offs = array("q", bytes(8 * (max_pairs + 1)))
    out_recs = bytearray(rec_cap)
    out_rec_offs = array("q", bytes(8 * (max_pairs + 1)))
    out_member = array("i", bytes(4 * max_pairs))
    out_pred = array("i", bytes(4 * max_pairs))
    out_kinds = bytearray(max_pairs)
    out_counts = array("i", bytes(4 * max_pairs))
    n_pairs = _LIB.batch_apply(
        a_m_offs, n_members, a_shapes, a_entities, a_pred_ids,
        a_objects, a_vtypes, a_voffs, a_vblob,
        pp_blob, a_pp_offs, pflags, pidents, n_preds,
        _ba_addr(out_keys), out_key_offs.buffer_info()[0],
        _ba_addr(out_recs), out_rec_offs.buffer_info()[0],
        out_member.buffer_info()[0], out_pred.buffer_info()[0],
        _ba_addr(out_kinds), out_counts.buffer_info()[0],
        max_pairs,
    )
    assert n_pairs >= 0, "batch_apply output caps overflowed"
    n_pairs = int(n_pairs)
    return (
        n_pairs,
        bytes(memoryview(out_keys)[: out_key_offs[n_pairs]]),
        out_key_offs,
        bytes(memoryview(out_recs)[: out_rec_offs[n_pairs]]),
        out_rec_offs,
        out_member,
        out_pred,
        out_kinds,
        out_counts,
    )


def vec_qi8_topk(
    codes, scales, offsets, csums, sqnorms, valid,
    qcodes, qscales, qoffsets, qcsums, qstats, metric: int, k: int,
):
    """Batched quantized full-corpus top-k (models/vector.py brute
    tier): nq queries scored against every valid row in one corpus
    pass, per-query fused top-k heaps, ascending (dist, row) with
    deterministic low-index tie-break. Returns (idx (nq, k) int64 with
    -1 padding, dist (nq, k) float32, n_valid) or None when the native
    lib is unavailable (caller takes the numpy fallback)."""
    if _LIB is None:
        return None
    codes = np.ascontiguousarray(codes, np.int8)
    qcodes = np.ascontiguousarray(qcodes, np.int8)
    nq = qcodes.shape[0]
    n, d = codes.shape
    # bind conversions to locals so temporaries outlive the call
    scales = np.ascontiguousarray(scales, np.float32)
    offsets = np.ascontiguousarray(offsets, np.float32)
    csums = np.ascontiguousarray(csums, np.int32)
    sqnorms = np.ascontiguousarray(sqnorms, np.float32)
    valid = np.ascontiguousarray(valid, np.uint8)
    qscales = np.ascontiguousarray(qscales, np.float32)
    qoffsets = np.ascontiguousarray(qoffsets, np.float32)
    qcsums = np.ascontiguousarray(qcsums, np.int32)
    qstats = np.ascontiguousarray(qstats, np.float32)
    out_idx = np.empty((nq, k), np.int64)
    out_dist = np.empty((nq, k), np.float32)
    nvalid = _LIB.vec_qi8_topk(
        _ptr(codes, ctypes.c_int8), n, d,
        _ptr(scales, ctypes.c_float), _ptr(offsets, ctypes.c_float),
        _ptr(csums, ctypes.c_int32), _ptr(sqnorms, ctypes.c_float),
        _ptr(valid, ctypes.c_uint8),
        _ptr(qcodes, ctypes.c_int8),
        _ptr(qscales, ctypes.c_float), _ptr(qoffsets, ctypes.c_float),
        _ptr(qcsums, ctypes.c_int32), _ptr(qstats, ctypes.c_float),
        nq, metric, k,
        _ptr(out_idx, ctypes.c_int64), _ptr(out_dist, ctypes.c_float),
    )
    return out_idx, out_dist, int(nvalid)


def vec_qi8_topk_idx(
    codes, scales, offsets, csums, sqnorms, valid, rows,
    qc, qscale, qoffset, qcsum, qstat, metric: int, k: int,
):
    """Quantized candidate-list top-k (the IVF probe): one query
    against the probed cells' concatenated row ids. Returns
    (idx (k,) int64 with -1 padding, dist (k,) float32, written) or
    None when the native lib is unavailable."""
    if _LIB is None:
        return None
    codes = np.ascontiguousarray(codes, np.int8)
    d = codes.shape[1]
    scales = np.ascontiguousarray(scales, np.float32)
    offsets = np.ascontiguousarray(offsets, np.float32)
    csums = np.ascontiguousarray(csums, np.int32)
    sqnorms = np.ascontiguousarray(sqnorms, np.float32)
    valid = np.ascontiguousarray(valid, np.uint8)
    rows = np.ascontiguousarray(rows, np.int32)
    qc = np.ascontiguousarray(qc, np.int8)
    out_idx = np.empty((k,), np.int64)
    out_dist = np.empty((k,), np.float32)
    wrote = _LIB.vec_qi8_topk_idx(
        _ptr(codes, ctypes.c_int8), d,
        _ptr(scales, ctypes.c_float), _ptr(offsets, ctypes.c_float),
        _ptr(csums, ctypes.c_int32), _ptr(sqnorms, ctypes.c_float),
        _ptr(valid, ctypes.c_uint8),
        _ptr(rows, ctypes.c_int32), rows.size,
        _ptr(qc, ctypes.c_int8),
        ctypes.c_float(float(qscale)), ctypes.c_float(float(qoffset)),
        int(qcsum), ctypes.c_float(float(qstat)),
        metric, k,
        _ptr(out_idx, ctypes.c_int64), _ptr(out_dist, ctypes.c_float),
    )
    return out_idx, out_dist, int(wrote)


def vec_qi8_topk_lists(
    codes, scales, offsets, csums, sqnorms, valid,
    rows, begs, ends,
    qcodes, qscales, qoffsets, qcsums, qstats,
    metric: int, k: int, nthreads: int = 1,
):
    """Batched quantized candidate-list top-k (the IVF probe batch and
    the top-2 cell-assignment fan): query q scores rows[begs[q]:ends[q]]
    of a shared candidate array — slices may alias. Scoring and
    tie-break identical to vec_qi8_topk_idx (a batch row is byte-equal
    to the solo call); threaded over queries. Returns (idx (nq, k)
    int64 with -1 padding, dist (nq, k) float32, candidates scanned)
    or None when the native lib is unavailable."""
    if _LIB is None:
        return None
    codes = np.ascontiguousarray(codes, np.int8)
    qcodes = np.ascontiguousarray(qcodes, np.int8)
    nq = qcodes.shape[0]
    d = codes.shape[1]
    scales = np.ascontiguousarray(scales, np.float32)
    offsets = np.ascontiguousarray(offsets, np.float32)
    csums = np.ascontiguousarray(csums, np.int32)
    sqnorms = np.ascontiguousarray(sqnorms, np.float32)
    valid = np.ascontiguousarray(valid, np.uint8)
    rows = np.ascontiguousarray(rows, np.int32)
    begs = np.ascontiguousarray(begs, np.int64)
    ends = np.ascontiguousarray(ends, np.int64)
    qscales = np.ascontiguousarray(qscales, np.float32)
    qoffsets = np.ascontiguousarray(qoffsets, np.float32)
    qcsums = np.ascontiguousarray(qcsums, np.int32)
    qstats = np.ascontiguousarray(qstats, np.float32)
    out_idx = np.empty((nq, k), np.int64)
    out_dist = np.empty((nq, k), np.float32)
    scanned = _LIB.vec_qi8_topk_lists(
        _ptr(codes, ctypes.c_int8), d,
        _ptr(scales, ctypes.c_float), _ptr(offsets, ctypes.c_float),
        _ptr(csums, ctypes.c_int32), _ptr(sqnorms, ctypes.c_float),
        _ptr(valid, ctypes.c_uint8),
        _ptr(rows, ctypes.c_int32),
        _ptr(begs, ctypes.c_int64), _ptr(ends, ctypes.c_int64),
        _ptr(qcodes, ctypes.c_int8),
        _ptr(qscales, ctypes.c_float), _ptr(qoffsets, ctypes.c_float),
        _ptr(qcsums, ctypes.c_int32), _ptr(qstats, ctypes.c_float),
        nq, metric, k, max(1, int(nthreads)),
        _ptr(out_idx, ctypes.c_int64), _ptr(out_dist, ctypes.c_float),
    )
    return out_idx, out_dist, int(scanned)


def vec_qi8_quantize(V, nthreads: int = 1):
    """Threaded int8 row quantizer (models/vector.py sidecar store):
    returns (codes i8, scales f32, offsets f32, csums i32, sqnorms f32)
    or None when the native lib is unavailable. Codes and sidecars are
    bit-identical to the numpy mirror; sqnorms agree to float32
    accumulation order."""
    if _LIB is None:
        return None
    V = np.ascontiguousarray(V, np.float32)
    n, d = V.shape
    codes = np.empty((n, d), np.int8)
    scales = np.empty((n,), np.float32)
    offsets = np.empty((n,), np.float32)
    csums = np.empty((n,), np.int32)
    sqnorms = np.empty((n,), np.float32)
    _LIB.vec_qi8_quantize(
        _ptr(V, ctypes.c_float), n, d, max(1, int(nthreads)),
        _ptr(codes, ctypes.c_int8), _ptr(scales, ctypes.c_float),
        _ptr(offsets, ctypes.c_float), _ptr(csums, ctypes.c_int32),
        _ptr(sqnorms, ctypes.c_float),
    )
    return codes, scales, offsets, csums, sqnorms


def _setop(name: str, a: np.ndarray, b: np.ndarray, out_size: int) -> np.ndarray:
    a = np.ascontiguousarray(a, np.uint64)
    b = np.ascontiguousarray(b, np.uint64)
    out = np.empty((out_size,), np.uint64)
    n = getattr(_LIB, name)(
        _ptr(a, ctypes.c_uint64),
        a.size,
        _ptr(b, ctypes.c_uint64),
        b.size,
        _ptr(out, ctypes.c_uint64),
    )
    return out[:n]


def intersect(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    if _LIB is None:
        return np.intersect1d(a, b, assume_unique=True)
    return _setop("intersect_u64", a, b, min(a.size, b.size))


def union(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    if _LIB is None:
        return np.union1d(a, b)
    return _setop("union_u64", a, b, a.size + b.size)


def difference(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    if _LIB is None:
        return np.setdiff1d(a, b, assume_unique=True)
    return _setop("difference_u64", a, b, a.size)


def merge_sorted(lists) -> np.ndarray:
    """K-way sorted union (ref algo/uidlist.go:448 MergeSorted)."""
    lists = [np.ascontiguousarray(x, np.uint64) for x in lists if len(x)]
    if not lists:
        return np.zeros((0,), np.uint64)
    if _LIB is None:
        return np.unique(np.concatenate(lists))
    flat = np.concatenate(lists)
    lens = np.asarray([x.size for x in lists], np.int64)
    total = int(flat.size)
    out = np.empty((total,), np.uint64)
    scratch = np.empty((total,), np.uint64)
    n = _LIB.merge_sorted_u64(
        _ptr(flat, ctypes.c_uint64),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        lens.size,
        _ptr(out, ctypes.c_uint64),
        _ptr(scratch, ctypes.c_uint64),
    )
    return out[:n]


def merge_sorted_flat(flat: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """K-way sorted union over an ALREADY-FLAT ragged buffer (row i is
    flat[sum(lens[:i]) : sum(lens[:i+1])], each row sorted) — the
    level-batched read form, skipping the per-row concatenate that
    merge_sorted() does. Falls back to numpy unique without the lib."""
    flat = np.ascontiguousarray(flat, np.uint64)
    lens = np.ascontiguousarray(lens, np.int64)
    if flat.size == 0:
        return np.zeros((0,), np.uint64)
    if _LIB is None:
        return np.unique(flat)
    # empty rows don't move flat but each would still cost two O(acc)
    # copies in merge_sorted_u64's fold — sparse wide levels are mostly
    # empty rows, so drop them first
    lens = lens[lens != 0]
    total = int(flat.size)
    out = np.empty((total,), np.uint64)
    scratch = np.empty((total,), np.uint64)
    n = _LIB.merge_sorted_u64(
        _ptr(flat, ctypes.c_uint64),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        lens.size,
        _ptr(out, ctypes.c_uint64),
        _ptr(scratch, ctypes.c_uint64),
    )
    return out[:n]


def sst_available() -> bool:
    return _LIB is not None


def sst_seek(buf: np.ndarray, end: int, off: int, key: bytes) -> int:
    kb = np.frombuffer(key, dtype=np.uint8)
    return int(
        _LIB.sst_seek(
            _ptr(buf, ctypes.c_uint8), end, off,
            _ptr(kb, ctypes.c_uint8), len(key),
        )
    )


def buf_ptr(arr: np.ndarray):
    """Stable uint8 pointer for a long-lived buffer (an SSTable mmap) —
    callers cache it so per-probe calls skip the numpy/ctypes marshaling
    that dominated the point-get profile."""
    return _ptr(arr, ctypes.c_uint8)


class _VerScratch(__import__("threading").local):
    """Reusable output arrays + cached pointers for sst_versions."""

    def __init__(self):
        self.cap = 0

    def ensure(self, cap: int):
        if cap <= self.cap:
            return
        self.cap = cap
        self.tss = np.empty(cap, np.uint64)
        self.seqs = np.empty(cap, np.uint64)
        self.voffs = np.empty(cap, np.int64)
        self.vlens = np.empty(cap, np.int64)
        self.ptrs = (
            _ptr(self.tss, ctypes.c_uint64),
            _ptr(self.seqs, ctypes.c_uint64),
            _ptr(self.voffs, ctypes.c_int64),
            _ptr(self.vlens, ctypes.c_int64),
        )


_VSCRATCH = _VerScratch()
_U8P = ctypes.POINTER(ctypes.c_uint8)


def sst_versions(
    buf: np.ndarray,
    end: int,
    off: int,
    key: bytes,
    cap: int = 64,
    bptr=None,
):
    """(tss, seqs, val_offs, val_lens) arrays for entries == key.
    Returned arrays are views into thread-local scratch — consume before
    the next call on this thread."""
    if bptr is None:
        bptr = _ptr(buf, ctypes.c_uint8)
    kp = ctypes.cast(ctypes.c_char_p(key), _U8P)
    s = _VSCRATCH
    while True:
        s.ensure(cap)
        n = int(
            _LIB.sst_versions(
                bptr, end, off, kp, len(key), s.cap, *s.ptrs
            )
        )
        if n < s.cap:
            return s.tss[:n], s.seqs[:n], s.voffs[:n], s.vlens[:n]
        cap = s.cap * 4


def sst_versions_multi(
    bptr, end: int, keys: list, starts: np.ndarray, cap: int
):
    """Batched version probe over SORTED distinct keys in one native call.
    Returns (counts, tss, seqs, voffs, vlens) flattened per key order."""
    nk = len(keys)
    blob = b"".join(keys)
    key_lens = np.fromiter((len(k) for k in keys), np.int64, nk)
    key_offs = np.zeros(nk, np.int64)
    np.cumsum(key_lens[:-1], out=key_offs[1:])
    kb = np.frombuffer(blob, np.uint8)
    while True:
        counts = np.zeros(nk, np.int64)
        tss = np.empty(cap, np.uint64)
        seqs = np.empty(cap, np.uint64)
        voffs = np.empty(cap, np.int64)
        vlens = np.empty(cap, np.int64)
        got = int(
            _LIB.sst_versions_multi(
                bptr, end, nk,
                _ptr(kb, ctypes.c_uint8),
                _ptr(key_offs, ctypes.c_int64),
                _ptr(key_lens, ctypes.c_int64),
                _ptr(np.ascontiguousarray(starts, np.int64), ctypes.c_int64),
                cap,
                _ptr(counts, ctypes.c_int64),
                _ptr(tss, ctypes.c_uint64), _ptr(seqs, ctypes.c_uint64),
                _ptr(voffs, ctypes.c_int64), _ptr(vlens, ctypes.c_int64),
            )
        )
        if got >= 0:
            return counts, tss[:got], seqs[:got], voffs[:got], vlens[:got]
        cap = max(cap * 2, -got + 1024)


def sst_scan(buf: np.ndarray, end: int, off: int, prefix: bytes, batch: int = 1024):
    """Yield (key_off, key_len, ts, seq, val_off, val_len) per entry while
    keys match `prefix`, scanning from `off`."""
    pb = np.frombuffer(prefix, dtype=np.uint8) if prefix else np.zeros(1, np.uint8)
    pos = off
    nxt = np.zeros(1, np.int64)
    while pos < end:
        koffs = np.empty(batch, np.int64)
        klens = np.empty(batch, np.int64)
        tss = np.empty(batch, np.uint64)
        seqs = np.empty(batch, np.uint64)
        voffs = np.empty(batch, np.int64)
        vlens = np.empty(batch, np.int64)
        n = int(
            _LIB.sst_scan(
                _ptr(buf, ctypes.c_uint8), end, pos,
                _ptr(pb, ctypes.c_uint8), len(prefix), batch,
                _ptr(koffs, ctypes.c_int64), _ptr(klens, ctypes.c_int64),
                _ptr(tss, ctypes.c_uint64), _ptr(seqs, ctypes.c_uint64),
                _ptr(voffs, ctypes.c_int64), _ptr(vlens, ctypes.c_int64),
                _ptr(nxt, ctypes.c_int64),
            )
        )
        for i in range(n):
            yield (
                int(koffs[i]), int(klens[i]), int(tss[i]), int(seqs[i]),
                int(voffs[i]), int(vlens[i]),
            )
        if n < batch:
            break
        pos = int(nxt[0])

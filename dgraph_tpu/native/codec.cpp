// Native host kernels: bit-pack codec + sorted-set algebra.
//
// The reference's performance-critical "near-native" pieces (SURVEY.md §2.7)
// are go-groupvarint's SSE decode (codec/codec.go:15) and the adaptive
// intersect loops (algo/uidlist.go). On the TPU build these live in two
// places: the device kernels (ops/setops.py) for batched query execution,
// and THIS file for the host-side paths — disk (de)serialization of UID
// packs and small singleton set ops where device dispatch isn't worth it.
//
// Built with -O3 -march=native when available; the auto-vectorizer turns
// the pack/unpack loops into SIMD shifts/masks (the groupvarint-equivalent).
// Exposed via ctypes (dgraph_tpu/native/__init__.py) — no pybind11 needed.

#include <cstdint>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------------
// Bit-packing: fixed-width lanes (ref codec.go packBlock; fixed-width instead
// of group-varint so decode is branch-free — see codec/uidpack.py docstring).
// ---------------------------------------------------------------------------

void bitpack(const uint32_t* vals, int64_t n, int width, uint8_t* out) {
    // out must be zeroed, size (n*width+7)/8
    uint64_t bitpos = 0;
    for (int64_t i = 0; i < n; i++) {
        uint64_t v = vals[i];
        uint64_t byte = bitpos >> 3;
        uint64_t shift = bitpos & 7;
        // write up to 5 bytes (width <= 32, shift <= 7)
        uint64_t cur = 0;
        memcpy(&cur, out + byte, 5);
        cur |= (v << shift);
        memcpy(out + byte, &cur, 5);
        bitpos += width;
    }
}

void bitunpack(const uint8_t* data, int64_t nbytes, int64_t n, int width,
               uint32_t* out) {
    uint64_t mask = (width >= 32) ? 0xFFFFFFFFull : ((1ull << width) - 1);
    uint64_t bitpos = 0;
    for (int64_t i = 0; i < n; i++) {
        uint64_t byte = bitpos >> 3;
        uint64_t shift = bitpos & 7;
        uint64_t window = 0;
        int64_t take = nbytes - (int64_t)byte;
        if (take > 8) take = 8;
        if (take > 0) memcpy(&window, data + byte, take);
        out[i] = (uint32_t)((window >> shift) & mask);
        bitpos += width;
    }
}

// Partial UidPack decode: materialize ONLY the listed blocks (codec/
// uidpack.py decode_blocks). offsets is the (nblocks, block_size) u32
// matrix; idxs are ascending block indices. Returns UIDs written.
int64_t pack_decode_blocks(const uint64_t* bases, const int32_t* counts,
                           const uint32_t* offsets, int64_t block_size,
                           const int64_t* idxs, int64_t nidx, uint64_t* out) {
    int64_t k = 0;
    for (int64_t i = 0; i < nidx; i++) {
        int64_t bi = idxs[i];
        uint64_t base = bases[bi];
        const uint32_t* row = offsets + bi * block_size;
        int64_t c = counts[bi];
        for (int64_t j = 0; j < c; j++) out[k++] = base + row[j];
    }
    return k;
}

// Level-batched fan-out fast path: decode N packs (one per parent uid of a
// traversal level) into ONE flat uid buffer + a per-pack prefix-offsets
// array in a single native pass. Per-pack pointer arrays avoid
// concatenating the block matrices host-side; out_offsets has npacks+1
// entries (out_offsets[p]..out_offsets[p+1] is pack p's row). Returns
// total UIDs written.
int64_t packs_decode_many(const uint64_t* const* bases,
                          const int32_t* const* counts,
                          const uint32_t* const* offsets,
                          const int64_t* nblocks, int64_t block_size,
                          int64_t npacks, uint64_t* out,
                          int64_t* out_offsets) {
    int64_t k = 0;
    for (int64_t p = 0; p < npacks; p++) {
        out_offsets[p] = k;
        const uint64_t* pb = bases[p];
        const int32_t* pc = counts[p];
        const uint32_t* po = offsets[p];
        int64_t nb = nblocks[p];
        for (int64_t bi = 0; bi < nb; bi++) {
            uint64_t base = pb[bi];
            const uint32_t* row = po + bi * block_size;
            int64_t c = pc[bi];
            for (int64_t j = 0; j < c; j++) out[k++] = base + row[j];
        }
    }
    out_offsets[npacks] = k;
    return k;
}

// Compressed-domain tiny-frontier intersect (ops/packed_setops.py small
// path; the scalar analog of algo/packed.go IntersectCompressedWithBin):
// for each frontier element binary-search its containing block by base,
// range-check against the block max, then binary-search the in-block
// offsets — the pack is never decoded. Writes hits to out; *touched_uids
// gets the summed count of distinct blocks probed (decode accounting).
int64_t pack_intersect_small(const uint64_t* bases, const int32_t* counts,
                             const uint32_t* offsets, int64_t block_size,
                             int64_t nblocks, const uint64_t* maxes,
                             const uint64_t* a, int64_t na, uint64_t* out,
                             int64_t* touched_uids) {
    int64_t k = 0, touched = 0, last_blk = -1;
    for (int64_t i = 0; i < na; i++) {
        uint64_t x = a[i];
        // last block with base <= x
        int64_t lo = 0, hi = nblocks;
        while (lo < hi) {
            int64_t mid = lo + ((hi - lo) >> 1);
            if (bases[mid] <= x) lo = mid + 1; else hi = mid;
        }
        int64_t bi = lo - 1;
        if (bi < 0 || x > maxes[bi]) continue;
        if (bi != last_blk) { touched += counts[bi]; last_blk = bi; }
        uint32_t off = (uint32_t)(x - bases[bi]);
        const uint32_t* row = offsets + bi * block_size;
        int64_t c = counts[bi], l = 0, h = c;
        while (l < h) {
            int64_t mid = l + ((h - l) >> 1);
            if (row[mid] < off) l = mid + 1; else h = mid;
        }
        if (l < c && row[l] == off) out[k++] = x;
    }
    *touched_uids = touched;
    return k;
}

// ---------------------------------------------------------------------------
// Sorted u64 set algebra (ref algo/uidlist.go IntersectWith:142 adaptive
// strategies; same linear/gallop split here).
// ---------------------------------------------------------------------------

static int64_t gallop(const uint64_t* arr, int64_t n, int64_t lo, uint64_t x) {
    // first index >= x, starting the search at lo
    int64_t step = 1, hi = lo + 1;
    while (hi < n && arr[hi] < x) {
        lo = hi;
        hi += step;
        step <<= 1;
    }
    if (hi > n) hi = n;
    // binary search in (lo, hi]
    while (lo < hi) {
        int64_t mid = lo + ((hi - lo) >> 1);
        if (arr[mid] < x) lo = mid + 1; else hi = mid;
    }
    return lo;
}

int64_t intersect_u64(const uint64_t* a, int64_t na, const uint64_t* b,
                      int64_t nb, uint64_t* out) {
    if (na > nb) { const uint64_t* t = a; a = b; b = t;
                   int64_t tn = na; na = nb; nb = tn; }
    int64_t k = 0;
    if (nb <= na * 32) {  // similar sizes: linear merge
        int64_t i = 0, j = 0;
        while (i < na && j < nb) {
            if (a[i] < b[j]) i++;
            else if (a[i] > b[j]) j++;
            else { out[k++] = a[i]; i++; j++; }
        }
    } else {  // ratio large: gallop the big side (IntersectWithJump/Bin)
        int64_t j = 0;
        for (int64_t i = 0; i < na; i++) {
            j = gallop(b, nb, j, a[i]);
            if (j < nb && b[j] == a[i]) out[k++] = a[i];
            if (j >= nb) break;
        }
    }
    return k;
}

int64_t union_u64(const uint64_t* a, int64_t na, const uint64_t* b,
                  int64_t nb, uint64_t* out) {
    int64_t i = 0, j = 0, k = 0;
    while (i < na && j < nb) {
        if (a[i] < b[j]) out[k++] = a[i++];
        else if (a[i] > b[j]) out[k++] = b[j++];
        else { out[k++] = a[i]; i++; j++; }
    }
    while (i < na) out[k++] = a[i++];
    while (j < nb) out[k++] = b[j++];
    return k;
}

int64_t difference_u64(const uint64_t* a, int64_t na, const uint64_t* b,
                       int64_t nb, uint64_t* out) {
    int64_t i = 0, j = 0, k = 0;
    while (i < na && j < nb) {
        if (a[i] < b[j]) out[k++] = a[i++];
        else if (a[i] > b[j]) j++;
        else { i++; j++; }
    }
    while (i < na) out[k++] = a[i++];
    return k;
}

// k-way merge via repeated 2-way (callers pass scratch; ref MergeSorted)
int64_t merge_sorted_u64(const uint64_t* flat, const int64_t* lens,
                         int64_t nlists, uint64_t* out, uint64_t* scratch) {
    int64_t acc = 0;  // current size in out
    int64_t off = 0;
    for (int64_t l = 0; l < nlists; l++) {
        int64_t n = lens[l];
        int64_t merged = union_u64(out, acc, flat + off, n, scratch);
        memcpy(out, scratch, merged * sizeof(uint64_t));
        acc = merged;
        off += n;
    }
    return acc;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// SSTable entry scans (storage/lsm.py plaintext format):
//   [u32 klen][u64 ts][u64 seq][u32 vlen][key bytes][val bytes]
// The Python per-entry struct unpacking dominated LSM reads; these scan
// in native code and hand back offsets for zero-copy value slicing.
// ---------------------------------------------------------------------------

extern "C" {

static inline int64_t ent_read(const uint8_t* buf, int64_t pos,
                               uint32_t* klen, uint64_t* ts, uint64_t* seq,
                               uint32_t* vlen) {
    memcpy(klen, buf + pos, 4);
    memcpy(ts, buf + pos + 4, 8);
    memcpy(seq, buf + pos + 12, 8);
    memcpy(vlen, buf + pos + 20, 4);
    return pos + 24;
}

static inline int keycmp(const uint8_t* a, int64_t na, const uint8_t* b,
                         int64_t nb) {
    int64_t n = na < nb ? na : nb;
    int c = memcmp(a, b, (size_t)n);
    if (c != 0) return c;
    return na < nb ? -1 : (na > nb ? 1 : 0);
}

// First entry offset with entry_key >= key, scanning from `off`.
int64_t sst_seek(const uint8_t* buf, int64_t end, int64_t off,
                 const uint8_t* key, int64_t klen) {
    int64_t pos = off;
    while (pos + 24 <= end) {
        uint32_t kl, vl; uint64_t ts, seq;
        int64_t body = ent_read(buf, pos, &kl, &ts, &seq, &vl);
        if (keycmp(buf + body, kl, key, klen) >= 0) return pos;
        pos = body + kl + vl;
    }
    return end;
}

// Versions of exactly `key` from `off` (which must be at/before the first
// match): writes (ts, seq, val_off, val_len) per version; returns count.
int64_t sst_versions(const uint8_t* buf, int64_t end, int64_t off,
                     const uint8_t* key, int64_t klen, int64_t max_out,
                     uint64_t* tss, uint64_t* seqs, int64_t* val_offs,
                     int64_t* val_lens) {
    int64_t pos = sst_seek(buf, end, off, key, klen);
    int64_t n = 0;
    while (pos + 24 <= end && n < max_out) {
        uint32_t kl, vl; uint64_t ts, seq;
        int64_t body = ent_read(buf, pos, &kl, &ts, &seq, &vl);
        if (keycmp(buf + body, kl, key, klen) != 0) break;
        tss[n] = ts;
        seqs[n] = seq;
        val_offs[n] = body + kl;
        val_lens[n] = vl;
        n++;
        pos = body + kl + vl;
    }
    return n;
}

// Entry headers from `off` while keys start with `prefix` (or all when
// prefix_len == 0): writes (key_off, key_len, ts, seq, val_off, val_len);
// returns count (callers loop with growing max_out).
// Versions of MANY sorted distinct keys in one pass. `starts[i]` is a
// seek hint at/before key i's first possible entry (sparse-index stride
// head); since keys ascend, the walk position is monotone — the scan for
// key i begins at max(current pos, starts[i]). Outputs are flattened:
// counts[i] versions for key i, written sequentially into tss/seqs/
// voffs/vlens. Returns total versions written, or -(needed) if max_out
// was too small (caller re-runs with a bigger buffer).
int64_t sst_versions_multi(const uint8_t* buf, int64_t end, int64_t nkeys,
                           const uint8_t* keys_blob, const int64_t* key_offs,
                           const int64_t* key_lens, const int64_t* starts,
                           int64_t max_out, int64_t* counts, uint64_t* tss,
                           uint64_t* seqs, int64_t* voffs, int64_t* vlens) {
    int64_t pos = 0;
    int64_t out = 0;
    for (int64_t i = 0; i < nkeys; i++) {
        const uint8_t* key = keys_blob + key_offs[i];
        int64_t klen = key_lens[i];
        if (starts[i] > pos) pos = starts[i];
        int64_t p = sst_seek(buf, end, pos, key, klen);
        int64_t n = 0;
        while (p + 24 <= end) {
            uint32_t kl, vl; uint64_t ts, seq;
            int64_t body = ent_read(buf, p, &kl, &ts, &seq, &vl);
            if (keycmp(buf + body, kl, key, klen) != 0) break;
            if (out + n >= max_out) return -(out + n + 1);
            tss[out + n] = ts;
            seqs[out + n] = seq;
            voffs[out + n] = body + kl;
            vlens[out + n] = vl;
            n++;
            p = body + kl + vl;
        }
        counts[i] = n;
        out += n;
        pos = p;
    }
    return out;
}

int64_t sst_scan(const uint8_t* buf, int64_t end, int64_t off,
                 const uint8_t* prefix, int64_t prefix_len, int64_t max_out,
                 int64_t* key_offs, int64_t* key_lens, uint64_t* tss,
                 uint64_t* seqs, int64_t* val_offs, int64_t* val_lens,
                 int64_t* next_pos) {
    int64_t pos = off;
    int64_t n = 0;
    while (pos + 24 <= end && n < max_out) {
        uint32_t kl, vl; uint64_t ts, seq;
        int64_t body = ent_read(buf, pos, &kl, &ts, &seq, &vl);
        if (prefix_len > 0) {
            if ((int64_t)kl < prefix_len ||
                memcmp(buf + body, prefix, (size_t)prefix_len) != 0) {
                break;
            }
        }
        key_offs[n] = body;
        key_lens[n] = kl;
        tss[n] = ts;
        seqs[n] = seq;
        val_offs[n] = body + kl;
        val_lens[n] = vl;
        n++;
        pos = body + kl + vl;
    }
    *next_pos = pos;
    return n;
}

}  // extern "C"

// Native host kernels: bit-pack codec + sorted-set algebra.
//
// The reference's performance-critical "near-native" pieces (SURVEY.md §2.7)
// are go-groupvarint's SSE decode (codec/codec.go:15) and the adaptive
// intersect loops (algo/uidlist.go). On the TPU build these live in two
// places: the device kernels (ops/setops.py) for batched query execution,
// and THIS file for the host-side paths — disk (de)serialization of UID
// packs and small singleton set ops where device dispatch isn't worth it.
//
// Built with -O3 -march=native when available; the auto-vectorizer turns
// the pack/unpack loops into SIMD shifts/masks (the groupvarint-equivalent).
// Exposed via ctypes (dgraph_tpu/native/__init__.py) — no pybind11 needed.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <cmath>
#include <string>
#include <thread>
#include <unordered_map>
#include <utility>
#include <vector>

#if defined(__AVX512VNNI__) || defined(__AVX512BW__) || defined(__AVX2__)
#include <immintrin.h>
#endif

extern "C" {

// ---------------------------------------------------------------------------
// Bit-packing: fixed-width lanes (ref codec.go packBlock; fixed-width instead
// of group-varint so decode is branch-free — see codec/uidpack.py docstring).
// ---------------------------------------------------------------------------

void bitpack(const uint32_t* vals, int64_t n, int width, uint8_t* out) {
    // out must be zeroed, size (n*width+7)/8
    uint64_t bitpos = 0;
    for (int64_t i = 0; i < n; i++) {
        uint64_t v = vals[i];
        uint64_t byte = bitpos >> 3;
        uint64_t shift = bitpos & 7;
        // write up to 5 bytes (width <= 32, shift <= 7)
        uint64_t cur = 0;
        memcpy(&cur, out + byte, 5);
        cur |= (v << shift);
        memcpy(out + byte, &cur, 5);
        bitpos += width;
    }
}

void bitunpack(const uint8_t* data, int64_t nbytes, int64_t n, int width,
               uint32_t* out) {
    uint64_t mask = (width >= 32) ? 0xFFFFFFFFull : ((1ull << width) - 1);
    uint64_t bitpos = 0;
    for (int64_t i = 0; i < n; i++) {
        uint64_t byte = bitpos >> 3;
        uint64_t shift = bitpos & 7;
        uint64_t window = 0;
        int64_t take = nbytes - (int64_t)byte;
        if (take > 8) take = 8;
        if (take > 0) memcpy(&window, data + byte, take);
        out[i] = (uint32_t)((window >> shift) & mask);
        bitpos += width;
    }
}

// Partial UidPack decode: materialize ONLY the listed blocks (codec/
// uidpack.py decode_blocks). offsets is the (nblocks, block_size) u32
// matrix; idxs are ascending block indices. Returns UIDs written.
int64_t pack_decode_blocks(const uint64_t* bases, const int32_t* counts,
                           const uint32_t* offsets, int64_t block_size,
                           const int64_t* idxs, int64_t nidx, uint64_t* out) {
    int64_t k = 0;
    for (int64_t i = 0; i < nidx; i++) {
        int64_t bi = idxs[i];
        uint64_t base = bases[bi];
        const uint32_t* row = offsets + bi * block_size;
        int64_t c = counts[bi];
        for (int64_t j = 0; j < c; j++) out[k++] = base + row[j];
    }
    return k;
}

// Level-batched fan-out fast path: decode N packs (one per parent uid of a
// traversal level) into ONE flat uid buffer + a per-pack prefix-offsets
// array in a single native pass. Per-pack pointer arrays avoid
// concatenating the block matrices host-side; out_offsets has npacks+1
// entries (out_offsets[p]..out_offsets[p+1] is pack p's row). Returns
// total UIDs written.
int64_t packs_decode_many(const uint64_t* const* bases,
                          const int32_t* const* counts,
                          const uint32_t* const* offsets,
                          const int64_t* nblocks, int64_t block_size,
                          int64_t npacks, uint64_t* out,
                          int64_t* out_offsets) {
    int64_t k = 0;
    for (int64_t p = 0; p < npacks; p++) {
        out_offsets[p] = k;
        const uint64_t* pb = bases[p];
        const int32_t* pc = counts[p];
        const uint32_t* po = offsets[p];
        int64_t nb = nblocks[p];
        for (int64_t bi = 0; bi < nb; bi++) {
            uint64_t base = pb[bi];
            const uint32_t* row = po + bi * block_size;
            int64_t c = pc[bi];
            for (int64_t j = 0; j < c; j++) out[k++] = base + row[j];
        }
    }
    out_offsets[npacks] = k;
    return k;
}

// Compressed-domain tiny-frontier intersect (ops/packed_setops.py small
// path; the scalar analog of algo/packed.go IntersectCompressedWithBin):
// for each frontier element binary-search its containing block by base,
// range-check against the block max, then binary-search the in-block
// offsets — the pack is never decoded. Writes hits to out; *touched_uids
// gets the summed count of distinct blocks probed (decode accounting).
int64_t pack_intersect_small(const uint64_t* bases, const int32_t* counts,
                             const uint32_t* offsets, int64_t block_size,
                             int64_t nblocks, const uint64_t* maxes,
                             const uint64_t* a, int64_t na, uint64_t* out,
                             int64_t* touched_uids) {
    int64_t k = 0, touched = 0, last_blk = -1;
    for (int64_t i = 0; i < na; i++) {
        uint64_t x = a[i];
        // last block with base <= x
        int64_t lo = 0, hi = nblocks;
        while (lo < hi) {
            int64_t mid = lo + ((hi - lo) >> 1);
            if (bases[mid] <= x) lo = mid + 1; else hi = mid;
        }
        int64_t bi = lo - 1;
        if (bi < 0 || x > maxes[bi]) continue;
        if (bi != last_blk) { touched += counts[bi]; last_blk = bi; }
        uint32_t off = (uint32_t)(x - bases[bi]);
        const uint32_t* row = offsets + bi * block_size;
        int64_t c = counts[bi], l = 0, h = c;
        while (l < h) {
            int64_t mid = l + ((h - l) >> 1);
            if (row[mid] < off) l = mid + 1; else h = mid;
        }
        if (l < c && row[l] == off) out[k++] = x;
    }
    *touched_uids = touched;
    return k;
}

// ---------------------------------------------------------------------------
// Adaptive set-representation engine (bitmap/packed hybrid containers).
//
// Blocks come in two container forms: sorted uint32 offsets (the encode
// default) and, for dense blocks, a fixed-size bitset over the block's
// u64 base (codec/uidpack.py block_bitmaps, Roaring-style per arxiv
// 1907.01032). The pair kernels below pick per BLOCK PAIR among
//   bitmap ^ bitmap    word-wise AND/ANDNOT + popcount extraction
//   bitmap x packed    probe the bitset while streaming the packed block
//   packed x packed    galloping/linear merge straight off the offsets
// so neither operand ever materializes to a flat u64 array (the "SIMD
// Compression and the Intersection of Sorted Integers" shape, arxiv
// 1401.6399). Word loops are written for the auto-vectorizer
// (-march=native: AVX2/NEON AND + popcount); scalar is the fallback.
// ---------------------------------------------------------------------------

// first index in row[0..n) with row[i] >= x, galloping from lo
static int64_t gallop32(const uint32_t* row, int64_t n, int64_t lo,
                        uint32_t x) {
    int64_t step = 1, hi = lo + 1;
    if (lo < n && row[lo] >= x) return lo;
    while (hi < n && row[hi] < x) {
        lo = hi;
        hi += step;
        step <<= 1;
    }
    if (hi > n) hi = n;
    while (lo < hi) {
        int64_t mid = lo + ((hi - lo) >> 1);
        if (row[mid] < x) lo = mid + 1; else hi = mid;
    }
    return lo;
}

// 64 bits of bitset `bm` (nwords words) starting at bit `bitoff`
static inline uint64_t bm_window(const uint64_t* bm, int64_t nwords,
                                 int64_t bitoff) {
    int64_t w = bitoff >> 6;
    int r = (int)(bitoff & 63);
    if (w >= nwords) return 0;
    uint64_t lo = bm[w] >> r;
    if (r && w + 1 < nwords) lo |= bm[w + 1] << (64 - r);
    return lo;
}

// Scatter eligible blocks' offsets into the COMPACT (n_eligible,
// bm_bits/64) bitset matrix. `rows[bi]` is block bi's row in out_words,
// or -1 for offsets-only blocks (eligibility is decided in ONE place,
// codec/uidpack.bitmap_eligible — the C++ side only scatters); out_words
// must be zeroed by the caller.
void pack_build_bitmaps(const int32_t* counts, const uint32_t* offsets,
                        int64_t block_size, int64_t nblocks,
                        const int32_t* rows, int64_t bm_bits,
                        uint64_t* out_words) {
    int64_t nw = bm_bits >> 6;
    for (int64_t bi = 0; bi < nblocks; bi++) {
        if (rows[bi] < 0) continue;
        uint64_t* w = out_words + (int64_t)rows[bi] * nw;
        const uint32_t* row = offsets + bi * block_size;
        int64_t c = counts[bi];
        for (int64_t j = 0; j < c; j++)
            w[row[j] >> 6] |= 1ull << (row[j] & 63);
    }
}

// kernel_counts layout shared by the engine entry points:
//   [0] bitmap^bitmap block pairs   [1] bitmap-probe block pairs
//   [2] packed-merge block pairs    [3] uids streamed compressed-domain
enum { KC_BITMAP = 0, KC_PROBE = 1, KC_GALLOP = 2, KC_STREAMED = 3 };

// Adaptive pack x pack set op entirely in the compressed domain.
// op: 0 = intersect, 1 = difference (a \ b). Walks the two block-range
// lists with a two-pointer skip (whole blocks outside the other operand's
// ranges are never touched — the packed-skip arm), and runs the cheapest
// kernel on each overlapping pair's window [max(bases), min(maxes)]
// (windows of consecutive pairs are disjoint, so each result uid is
// emitted exactly once, in order). Returns uids written to out.
int64_t pack_pair_setop(
    int op,
    const uint64_t* a_bases, const int32_t* a_counts,
    const uint32_t* a_offsets, int64_t a_block_size, int64_t a_nblocks,
    const uint64_t* a_maxes, const uint64_t* a_bm, const int32_t* a_bm_rows,
    const uint64_t* b_bases, const int32_t* b_counts,
    const uint32_t* b_offsets, int64_t b_block_size, int64_t b_nblocks,
    const uint64_t* b_maxes, const uint64_t* b_bm, const int32_t* b_bm_rows,
    int64_t bm_bits, uint64_t* out, int64_t* kernel_counts) {
    int64_t nw = bm_bits >> 6;
    int64_t ai = 0, bi = 0, k = 0;
    int64_t last_a = -1, last_b = -1;
    int64_t ia_cur = 0;  // difference: next unemitted offset of block ai
    // monotone in-block search hints: windows over one block ascend, so
    // a later window's lower_bound can start where the previous ended
    // (turns a block spanning many peer blocks into one amortized scan)
    int64_t ja_hint = 0, jb_hint = 0;
    while (ai < a_nblocks && bi < b_nblocks) {
        if (a_maxes[ai] < b_bases[bi]) {
            // a block wholly below every remaining b block
            if (op == 1) {
                const uint32_t* row = a_offsets + ai * a_block_size;
                for (int64_t j = ia_cur; j < a_counts[ai]; j++)
                    out[k++] = a_bases[ai] + row[j];
            }
            ai++; ia_cur = 0; ja_hint = 0;
            continue;
        }
        if (b_maxes[bi] < a_bases[ai]) { bi++; jb_hint = 0; continue; }
        uint64_t lo = a_bases[ai] > b_bases[bi] ? a_bases[ai] : b_bases[bi];
        uint64_t hi = a_maxes[ai] < b_maxes[bi] ? a_maxes[ai] : b_maxes[bi];
        if (ai != last_a) {
            kernel_counts[KC_STREAMED] += a_counts[ai]; last_a = ai;
        }
        if (bi != last_b) {
            kernel_counts[KC_STREAMED] += b_counts[bi]; last_b = bi;
        }
        const uint32_t* arow = a_offsets + ai * a_block_size;
        const uint32_t* brow = b_offsets + bi * b_block_size;
        int64_t ac = a_counts[ai], bc = b_counts[bi];
        uint32_t alo = (uint32_t)(lo - a_bases[ai]);
        uint32_t ahi = (uint32_t)(hi - a_bases[ai]);
        if (op == 1) {
            // flush a elements below the window (no b block can hold them)
            while (ia_cur < ac && arow[ia_cur] < alo)
                out[k++] = a_bases[ai] + arow[ia_cur++];
        }
        int abm = a_bm_rows && a_bm_rows[ai] >= 0;
        int bbm = b_bm_rows && b_bm_rows[bi] >= 0;
        if (abm && bbm) {
            // bitmap ^ bitmap: word-wise AND / ANDNOT over the window
            kernel_counts[KC_BITMAP]++;
            int64_t span = (int64_t)(hi - lo) + 1;
            const uint64_t* aw = a_bm + (int64_t)a_bm_rows[ai] * nw;
            const uint64_t* bw = b_bm + (int64_t)b_bm_rows[bi] * nw;
            int64_t aoff = (int64_t)(lo - a_bases[ai]);
            int64_t boff = (int64_t)(lo - b_bases[bi]);
            for (int64_t p = 0; p < span; p += 64) {
                uint64_t wa = bm_window(aw, nw, aoff + p);
                uint64_t wb = bm_window(bw, nw, boff + p);
                uint64_t w = op == 0 ? (wa & wb) : (wa & ~wb);
                if (span - p < 64) w &= (1ull << (span - p)) - 1;
                while (w) {
                    out[k++] = lo + p + __builtin_ctzll(w);
                    w &= w - 1;
                }
            }
            if (op == 1) {
                while (ia_cur < ac && arow[ia_cur] <= ahi) ia_cur++;
            }
        } else if (bbm || (op == 0 && abm)) {
            // bitmap x packed: stream the packed side's offsets through
            // the window, probe the bitset (O(1) per element). For
            // difference only b-as-bitmap streams this way (a's elements
            // must drive the output order).
            kernel_counts[KC_PROBE]++;
            if (op == 0 && !bbm) {
                // a is the bitmap: stream b's offsets, probe a's bits
                const uint64_t* aw = a_bm + (int64_t)a_bm_rows[ai] * nw;
                int64_t j = gallop32(brow, bc, jb_hint,
                                     (uint32_t)(lo - b_bases[bi]));
                uint32_t bhi = (uint32_t)(hi - b_bases[bi]);
                for (; j < bc && brow[j] <= bhi; j++) {
                    uint64_t off = b_bases[bi] + brow[j] - a_bases[ai];
                    if ((aw[off >> 6] >> (off & 63)) & 1)
                        out[k++] = b_bases[bi] + brow[j];
                }
                jb_hint = j;
            } else {
                const uint64_t* bw = b_bm + (int64_t)b_bm_rows[bi] * nw;
                int64_t j = op == 1 ? ia_cur
                                    : gallop32(arow, ac, ja_hint, alo);
                for (; j < ac && arow[j] <= ahi; j++) {
                    uint64_t off = a_bases[ai] + arow[j] - b_bases[bi];
                    int hit = (bw[off >> 6] >> (off & 63)) & 1;
                    if (hit == (op == 0)) out[k++] = a_bases[ai] + arow[j];
                }
                ja_hint = j;
                if (op == 1) ia_cur = j;
            }
        } else {
            // packed x packed: merge the two offset spans in the window
            // without decoding; gallop the long side when skewed
            kernel_counts[KC_GALLOP]++;
            int64_t ja = op == 1 ? ia_cur
                                 : gallop32(arow, ac, ja_hint, alo);
            int64_t jb = gallop32(brow, bc, jb_hint,
                                  (uint32_t)(lo - b_bases[bi]));
            uint32_t bhi = (uint32_t)(hi - b_bases[bi]);
            int64_t abase_rel = (int64_t)(a_bases[ai] - lo);
            int64_t bbase_rel = (int64_t)(b_bases[bi] - lo);
            while (ja < ac && jb < bc && arow[ja] <= ahi &&
                   brow[jb] <= bhi) {
                // compare in window-local space (bases differ per block)
                int64_t va = abase_rel + arow[ja];
                int64_t vb = bbase_rel + brow[jb];
                if (va < vb) {
                    if (op == 1) out[k++] = a_bases[ai] + arow[ja];
                    ja++;
                } else if (va > vb) {
                    jb++;
                    // skewed spans: gallop b forward to a's current value
                    if (jb < bc &&
                        bbase_rel + brow[jb] < abase_rel + arow[ja])
                        jb = gallop32(brow, bc, jb,
                                      (uint32_t)(va - bbase_rel));
                } else {
                    if (op == 0) out[k++] = a_bases[ai] + arow[ja];
                    ja++; jb++;
                }
            }
            if (op == 1) {
                // remaining a elements inside the window have no b peer
                while (ja < ac && arow[ja] <= ahi)
                    out[k++] = a_bases[ai] + arow[ja++];
                ia_cur = ja;
            }
            ja_hint = ja;
            jb_hint = jb;
        }
        if (a_maxes[ai] <= b_maxes[bi]) { ai++; ia_cur = 0; ja_hint = 0; }
        else { bi++; jb_hint = 0; }
    }
    if (op == 1) {
        // b exhausted (or never overlapped): the rest of a survives
        while (ai < a_nblocks) {
            const uint32_t* row = a_offsets + ai * a_block_size;
            for (int64_t j = ia_cur; j < a_counts[ai]; j++)
                out[k++] = a_bases[ai] + row[j];
            ai++; ia_cur = 0;
        }
    }
    return k;
}

// Adaptive sorted-array x pack set op: stream `a` against the pack's
// blocks with a monotone block cursor — per block, probe the bitset when
// the block carries one, else merge against the sorted offsets. The pack
// is never decoded. op: 0 = intersect, 1 = difference (a \ pack).
int64_t pack_stream_setop(
    int op, const uint64_t* a, int64_t na,
    const uint64_t* bases, const int32_t* counts, const uint32_t* offsets,
    int64_t block_size, int64_t nblocks, const uint64_t* maxes,
    const uint64_t* bm, const int32_t* bm_rows, int64_t bm_bits,
    uint64_t* out, int64_t* kernel_counts) {
    int64_t nw = bm_bits >> 6;
    int64_t ia = 0, bi = 0, k = 0;
    while (ia < na) {
        uint64_t x = a[ia];
        while (bi < nblocks && maxes[bi] < x) bi++;
        if (bi == nblocks) {
            if (op == 1) while (ia < na) out[k++] = a[ia++];
            break;
        }
        if (x < bases[bi]) {
            if (op == 1) {
                while (ia < na && a[ia] < bases[bi]) out[k++] = a[ia++];
            } else {
                // gallop a forward to the block's start
                int64_t step = 1, hi2 = ia + 1;
                while (hi2 < na && a[hi2] < bases[bi]) {
                    ia = hi2; hi2 += step; step <<= 1;
                }
                if (hi2 > na) hi2 = na;
                while (ia < hi2) {
                    int64_t mid = ia + ((hi2 - ia) >> 1);
                    if (a[mid] < bases[bi]) ia = mid + 1; else hi2 = mid;
                }
            }
            continue;
        }
        // a run of `a` lands in block bi
        kernel_counts[KC_STREAMED] += counts[bi];
        const uint32_t* row = offsets + bi * block_size;
        int64_t c = counts[bi];
        if (bm_rows && bm_rows[bi] >= 0) {
            kernel_counts[KC_PROBE]++;
            const uint64_t* w = bm + (int64_t)bm_rows[bi] * nw;
            while (ia < na && a[ia] <= maxes[bi]) {
                uint64_t off = a[ia] - bases[bi];
                int hit = (int)((w[off >> 6] >> (off & 63)) & 1);
                if (hit == (op == 0)) out[k++] = a[ia];
                ia++;
            }
        } else {
            kernel_counts[KC_GALLOP]++;
            int64_t j = 0;
            while (ia < na && a[ia] <= maxes[bi]) {
                uint32_t off = (uint32_t)(a[ia] - bases[bi]);
                j = gallop32(row, c, j, off);
                int hit = (j < c && row[j] == off);
                if (hit == (op == 0)) out[k++] = a[ia];
                ia++;
            }
        }
        bi++;
    }
    return k;
}

// ---------------------------------------------------------------------------
// Sorted u64 set algebra (ref algo/uidlist.go IntersectWith:142 adaptive
// strategies; same linear/gallop split here).
// ---------------------------------------------------------------------------

static int64_t gallop(const uint64_t* arr, int64_t n, int64_t lo, uint64_t x) {
    // first index >= x, starting the search at lo
    int64_t step = 1, hi = lo + 1;
    while (hi < n && arr[hi] < x) {
        lo = hi;
        hi += step;
        step <<= 1;
    }
    if (hi > n) hi = n;
    // binary search in (lo, hi]
    while (lo < hi) {
        int64_t mid = lo + ((hi - lo) >> 1);
        if (arr[mid] < x) lo = mid + 1; else hi = mid;
    }
    return lo;
}

int64_t intersect_u64(const uint64_t* a, int64_t na, const uint64_t* b,
                      int64_t nb, uint64_t* out) {
    if (na > nb) { const uint64_t* t = a; a = b; b = t;
                   int64_t tn = na; na = nb; nb = tn; }
    int64_t k = 0;
    if (nb <= na * 32) {  // similar sizes: linear merge
        int64_t i = 0, j = 0;
        while (i < na && j < nb) {
            if (a[i] < b[j]) i++;
            else if (a[i] > b[j]) j++;
            else { out[k++] = a[i]; i++; j++; }
        }
    } else {  // ratio large: gallop the big side (IntersectWithJump/Bin)
        int64_t j = 0;
        for (int64_t i = 0; i < na; i++) {
            j = gallop(b, nb, j, a[i]);
            if (j < nb && b[j] == a[i]) out[k++] = a[i];
            if (j >= nb) break;
        }
    }
    return k;
}

int64_t union_u64(const uint64_t* a, int64_t na, const uint64_t* b,
                  int64_t nb, uint64_t* out) {
    int64_t i = 0, j = 0, k = 0;
    while (i < na && j < nb) {
        if (a[i] < b[j]) out[k++] = a[i++];
        else if (a[i] > b[j]) out[k++] = b[j++];
        else { out[k++] = a[i]; i++; j++; }
    }
    while (i < na) out[k++] = a[i++];
    while (j < nb) out[k++] = b[j++];
    return k;
}

int64_t difference_u64(const uint64_t* a, int64_t na, const uint64_t* b,
                       int64_t nb, uint64_t* out) {
    int64_t i = 0, j = 0, k = 0;
    while (i < na && j < nb) {
        if (a[i] < b[j]) out[k++] = a[i++];
        else if (a[i] > b[j]) j++;
        else { i++; j++; }
    }
    while (i < na) out[k++] = a[i++];
    return k;
}

// k-way merge via repeated 2-way (callers pass scratch; ref MergeSorted)
int64_t merge_sorted_u64(const uint64_t* flat, const int64_t* lens,
                         int64_t nlists, uint64_t* out, uint64_t* scratch) {
    int64_t acc = 0;  // current size in out
    int64_t off = 0;
    for (int64_t l = 0; l < nlists; l++) {
        int64_t n = lens[l];
        int64_t merged = union_u64(out, acc, flat + off, n, scratch);
        memcpy(out, scratch, merged * sizeof(uint64_t));
        acc = merged;
        off += n;
    }
    return acc;
}

// ---------------------------------------------------------------------------
// Quantized vector scoring (models/vector.py quantized engine).
//
// Corpus rows are stored as per-row asymmetric int8: v_ij ~= s_i*c_ij + o_i
// (scale/offset sidecars, plus the EXACT float32 sqnorm of the original
// row). The query is quantized once per call the same way
// (q_j ~= sq*qc_j + oq), so the reconstructed dot product is
//
//   dot(v_i, q) ~= sq*(s_i*dot8(c_i,qc) + o_i*qcsum) + oq*(s_i*csum_i + d*o_i)
//
// where dot8 is the int8 x int8 -> int32 inner product (the only O(d)
// term — it auto-vectorizes to the wide integer-multiply-add forms under
// -march=native, and a row costs 1 byte/component of memory traffic
// instead of the float path's 4). csum_i / qcsum are precomputed code
// sums. Distances reconstructed per metric (0 = squared euclidean,
// 1 = cosine, 2 = negated dot) use the exact sqnorm sidecar, so only
// the dot term carries quantization error — the caller reranks the
// surviving pool in float32 (models/vector.py) to recover exact order.
//
// Both kernels fuse a partial top-k: a per-query max-heap of size k
// (worst kept at the root) lives directly in the caller's output slabs,
// and is heap-sorted ascending before return. Ties break toward the
// LOWER row index — deterministic output for duplicate vectors, which
// the solo-vs-coalesced byte-identity contract relies on.
// ---------------------------------------------------------------------------

// "worse" ordering for the heaps: larger distance, then larger index
static inline int vq_worse(float da, int64_t ia, float db, int64_t ib) {
    return da > db || (da == db && ia > ib);
}

// replace the root with (dv, iv) and sift down over [0, len)
static void vq_sift(float* hd, int64_t* hi, int64_t len, float dv,
                    int64_t iv) {
    int64_t p = 0;
    for (;;) {
        int64_t c = 2 * p + 1;
        if (c >= len) break;
        if (c + 1 < len && vq_worse(hd[c + 1], hi[c + 1], hd[c], hi[c]))
            c++;
        if (!vq_worse(hd[c], hi[c], dv, iv)) break;
        hd[p] = hd[c];
        hi[p] = hi[c];
        p = c;
    }
    hd[p] = dv;
    hi[p] = iv;
}

// heap-sort the k slots ascending (dist, then index); empty slots
// (+inf, -1) end up trailing
static void vq_heapsort(float* hd, int64_t* hi, int64_t k) {
    for (int64_t end = k - 1; end > 0; end--) {
        float dv = hd[end];
        int64_t iv = hi[end];
        hd[end] = hd[0];
        hi[end] = hi[0];
        vq_sift(hd, hi, end, dv, iv);
    }
}

// int8 x int8 -> int32 inner product between the query codes `q` and a
// corpus row `c` whose code sum is `csum_c`. All paths produce the SAME
// integer result (products and sums are exact), so kernel output does
// not depend on which SIMD tier the build machine has.
//
// The VNNI path uses vpdpbusd, which wants unsigned x signed: the query
// side is biased to unsigned on the fly (q + 128 == q ^ 0x80 on int8)
// and the bias is removed with the row's precomputed code sum:
// sum((q+128)*c) - 128*sum(c) == sum(q*c).
static inline int32_t vq_dot8(const int8_t* q, const int8_t* c, int64_t d,
                              int32_t csum_c) {
#if defined(__AVX512VNNI__)
    __m512i acc = _mm512_setzero_si512();
    const __m512i bias = _mm512_set1_epi8((char)0x80);
    int64_t j = 0;
    for (; j + 64 <= d; j += 64) {
        __m512i vq = _mm512_xor_si512(
            _mm512_loadu_si512((const void*)(q + j)), bias);
        __m512i vc = _mm512_loadu_si512((const void*)(c + j));
        acc = _mm512_dpbusd_epi32(acc, vq, vc);
    }
    int32_t r = _mm512_reduce_add_epi32(acc);
    // tail stays in biased space so one correction covers everything
    for (; j < d; j++)
        r += ((int32_t)q[j] + 128) * (int32_t)c[j];
    return r - 128 * csum_c;
#elif defined(__AVX512BW__)
    (void)csum_c;
    __m512i acc = _mm512_setzero_si512();
    int64_t j = 0;
    for (; j + 32 <= d; j += 32) {
        __m512i vq = _mm512_cvtepi8_epi16(
            _mm256_loadu_si256((const __m256i*)(q + j)));
        __m512i vc = _mm512_cvtepi8_epi16(
            _mm256_loadu_si256((const __m256i*)(c + j)));
        acc = _mm512_add_epi32(acc, _mm512_madd_epi16(vq, vc));
    }
    int32_t r = _mm512_reduce_add_epi32(acc);
    for (; j < d; j++) r += (int32_t)q[j] * (int32_t)c[j];
    return r;
#elif defined(__AVX2__)
    (void)csum_c;
    __m256i acc = _mm256_setzero_si256();
    int64_t j = 0;
    for (; j + 16 <= d; j += 16) {
        __m256i vq = _mm256_cvtepi8_epi16(
            _mm_loadu_si128((const __m128i*)(q + j)));
        __m256i vc = _mm256_cvtepi8_epi16(
            _mm_loadu_si128((const __m128i*)(c + j)));
        acc = _mm256_add_epi32(acc, _mm256_madd_epi16(vq, vc));
    }
    __m128i lo = _mm256_castsi256_si128(acc);
    __m128i hi = _mm256_extracti128_si256(acc, 1);
    __m128i s = _mm_add_epi32(lo, hi);
    s = _mm_add_epi32(s, _mm_srli_si128(s, 8));
    s = _mm_add_epi32(s, _mm_srli_si128(s, 4));
    int32_t r = _mm_cvtsi128_si32(s);
    for (; j < d; j++) r += (int32_t)q[j] * (int32_t)c[j];
    return r;
#else
    (void)csum_c;
    int32_t acc = 0;
    for (int64_t j = 0; j < d; j++)
        acc += (int32_t)q[j] * (int32_t)c[j];
    return acc;
#endif
}

static inline float vq_dist(int metric, float dot, float sqn, float vn,
                            float qstat) {
    if (metric == 0) return sqn - 2.0f * dot + qstat;  // squared euclidean
    if (metric == 1) {                                 // cosine
        float denom = vn * qstat;                      // qstat = |q|
        if (denom < 1e-12f) denom = 1e-12f;
        return 1.0f - dot / denom;
    }
    return -dot;                                       // dotproduct
}

// Batched full-corpus scan: nq queries against n rows in ONE pass (the
// corpus is read once per batch — the 768-byte row stays in L1 across
// the query loop). valid[i] == 0 skips tombstoned rows. Per query q,
// out_idx/out_dist rows q*k..q*k+k hold the top-k ascending; unused
// slots are (-1, +inf). qstats[q] is the exact q.q (euclidean) or |q|
// (cosine). Returns the number of valid rows scanned.
int64_t vec_qi8_topk(
    const int8_t* codes, int64_t n, int64_t d,
    const float* scales, const float* offsets, const int32_t* csums,
    const float* sqnorms, const uint8_t* valid,
    const int8_t* qcodes, const float* qscales, const float* qoffsets,
    const int32_t* qcsums, const float* qstats,
    int64_t nq, int metric, int64_t k,
    int64_t* out_idx, float* out_dist) {
    const float inf = __builtin_inff();
    for (int64_t t = 0; t < nq * k; t++) {
        out_idx[t] = -1;
        out_dist[t] = inf;
    }
    int64_t nvalid = 0;
    for (int64_t i = 0; i < n; i++) {
        if (valid && !valid[i]) continue;
        nvalid++;
        const int8_t* row = codes + i * d;
        float s = scales[i];
        float o = offsets[i];
        int32_t cs_i = csums[i];
        float cs = (float)cs_i;
        float sqn = sqnorms[i];
        float vn = metric == 1 ? __builtin_sqrtf(sqn) : 0.0f;
        for (int64_t q = 0; q < nq; q++) {
            int32_t d8 = vq_dot8(qcodes + q * d, row, d, cs_i);
            float dot = qscales[q] * (s * (float)d8 + o * (float)qcsums[q])
                      + qoffsets[q] * (s * cs + (float)d * o);
            float dist = vq_dist(metric, dot, sqn, vn, qstats[q]);
            float* hd = out_dist + q * k;
            int64_t* hi = out_idx + q * k;
            if (vq_worse(hd[0], hi[0], dist, i))
                vq_sift(hd, hi, k, dist, i);
        }
    }
    for (int64_t q = 0; q < nq; q++)
        vq_heapsort(out_dist + q * k, out_idx + q * k, k);
    return nvalid;
}

// Candidate-list scan (the IVF probe): one query against an explicit
// row-id list (the probed cells' concatenated ids). Same scoring,
// heap, and tie-break as the full scan. Returns entries written
// (min(k, valid candidates)).
int64_t vec_qi8_topk_idx(
    const int8_t* codes, int64_t d,
    const float* scales, const float* offsets, const int32_t* csums,
    const float* sqnorms, const uint8_t* valid,
    const int32_t* rows, int64_t nrows,
    const int8_t* qc, float qscale, float qoffset, int32_t qcsum,
    float qstat, int metric, int64_t k,
    int64_t* out_idx, float* out_dist) {
    const float inf = __builtin_inff();
    for (int64_t t = 0; t < k; t++) {
        out_idx[t] = -1;
        out_dist[t] = inf;
    }
    int64_t nvalid = 0;
    for (int64_t t = 0; t < nrows; t++) {
        int64_t i = rows[t];
        // candidate rows are scattered through the code matrix (the
        // scan is DRAM-latency-bound at ~3 GB/s without this); pull the
        // row a few candidates ahead into L2 while scoring this one
        if (t + 12 < nrows) {
            const int8_t* pr = codes + (int64_t)rows[t + 12] * d;
            for (int64_t pj = 0; pj < d; pj += 64)
                __builtin_prefetch(pr + pj, 0, 1);
        }
        if (valid && !valid[i]) continue;
        nvalid++;
        const int8_t* row = codes + i * d;
        int32_t d8 = vq_dot8(qc, row, d, csums[i]);
        float s = scales[i];
        float o = offsets[i];
        float dot = qscale * (s * (float)d8 + o * (float)qcsum)
                  + qoffset * (s * (float)csums[i] + (float)d * o);
        float sqn = sqnorms[i];
        float vn = metric == 1 ? __builtin_sqrtf(sqn) : 0.0f;
        float dist = vq_dist(metric, dot, sqn, vn, qstat);
        if (vq_worse(out_dist[0], out_idx[0], dist, i))
            vq_sift(out_dist, out_idx, k, dist, i);
    }
    vq_heapsort(out_dist, out_idx, k);
    return nvalid < k ? nvalid : k;
}

}  // extern "C"

// Run fn(t) on nt threads (nt==1 stays inline — no spawn cost on the
// small-corpus paths and under sanitizers that dislike short threads).
template <typename F>
static void vq_parallel(int64_t nt, F fn) {
    if (nt <= 1) {
        fn(0);
        return;
    }
    std::vector<std::thread> ths;
    ths.reserve((size_t)(nt - 1));
    for (int64_t t = 1; t < nt; t++) ths.emplace_back(fn, t);
    fn(0);
    for (auto& th : ths) th.join();
}

extern "C" {

// Batched candidate-list scan: nq queries, each against its OWN slice
// rows[begs[q]..ends[q]) of a shared candidate-id array (the probed IVF
// cells in CSR form; slices may alias — the top-2 cell assignment path
// points many queries at one shared per-group centroid list). Scoring,
// heap, and (dist, row) tie-break identical to vec_qi8_topk_idx, so a
// batch row is byte-identical to the solo call — the coalescing
// contract. Threaded over queries (each query's heap lives in its own
// out slab — no sharing); returns total valid candidates scored.
int64_t vec_qi8_topk_lists(
    const int8_t* codes, int64_t d,
    const float* scales, const float* offsets, const int32_t* csums,
    const float* sqnorms, const uint8_t* valid,
    const int32_t* rows, const int64_t* begs, const int64_t* ends,
    const int8_t* qcodes, const float* qscales, const float* qoffsets,
    const int32_t* qcsums, const float* qstats,
    int64_t nq, int metric, int64_t k, int64_t nthreads,
    int64_t* out_idx, float* out_dist) {
    const float inf = __builtin_inff();
    int64_t nt = nthreads < 1 ? 1 : nthreads;
    if (nt > nq) nt = nq < 1 ? 1 : nq;
    if (nt > 64) nt = 64;
    std::vector<int64_t> scanned((size_t)nt, 0);
    vq_parallel(nt, [&](int64_t t) {
        int64_t lo = nq * t / nt, hi = nq * (t + 1) / nt;
        int64_t nvalid = 0;
        for (int64_t q = lo; q < hi; q++) {
            float* hd = out_dist + q * k;
            int64_t* hi_ = out_idx + q * k;
            for (int64_t s = 0; s < k; s++) {
                hi_[s] = -1;
                hd[s] = inf;
            }
            const int8_t* qc = qcodes + q * d;
            float qscale = qscales[q], qoffset = qoffsets[q];
            float qcsum = (float)qcsums[q], qstat = qstats[q];
            for (int64_t s = begs[q]; s < ends[q]; s++) {
                int64_t i = rows[s];
                // same scattered-row prefetch as vec_qi8_topk_idx
                if (s + 12 < ends[q]) {
                    const int8_t* pr = codes + (int64_t)rows[s + 12] * d;
                    for (int64_t pj = 0; pj < d; pj += 64)
                        __builtin_prefetch(pr + pj, 0, 1);
                }
                if (valid && !valid[i]) continue;
                nvalid++;
                int32_t d8 = vq_dot8(qc, codes + i * d, d, csums[i]);
                float sc = scales[i], o = offsets[i];
                float dot = qscale * (sc * (float)d8 + o * qcsum)
                          + qoffset * (sc * (float)csums[i] + (float)d * o);
                float sqn = sqnorms[i];
                float vn = metric == 1 ? __builtin_sqrtf(sqn) : 0.0f;
                float dist = vq_dist(metric, dot, sqn, vn, qstat);
                if (vq_worse(hd[0], hi_[0], dist, i))
                    vq_sift(hd, hi_, k, dist, i);
            }
            vq_heapsort(hd, hi_, k);
        }
        scanned[(size_t)t] = nvalid;
    });
    int64_t total = 0;
    for (int64_t t = 0; t < nt; t++) total += scanned[(size_t)t];
    return total;
}

// Row quantizer for the int8 sidecar store: per-row asymmetric
// v ~= scale*code + offset with codes in [-127, 127], plus the code sum
// and exact float32 squared norm. Bit-identical codes/scales/offsets/
// csums to the numpy mirror in models/vector.py _quantize (same f32 op
// order; rintf under the default round-to-nearest-even mode matches
// np.rint); sqnorms may differ in final ulps (sequential vs pairwise
// accumulation) — consumers rerank in float32, so ordering is immune.
// Threaded over row ranges; returns n.
int64_t vec_qi8_quantize(
    const float* V, int64_t n, int64_t d, int64_t nthreads,
    int8_t* codes, float* scales, float* offsets, int32_t* csums,
    float* sqnorms) {
    int64_t nt = nthreads < 1 ? 1 : nthreads;
    if (nt > n) nt = n < 1 ? 1 : n;
    if (nt > 64) nt = 64;
    vq_parallel(nt, [&](int64_t t) {
        int64_t lo = n * t / nt, hi = n * (t + 1) / nt;
        for (int64_t i = lo; i < hi; i++) {
            const float* row = V + i * d;
            float mn = row[0], mx = row[0];
            float sq = 0.0f;
            for (int64_t j = 0; j < d; j++) {
                float v = row[j];
                if (v < mn) mn = v;
                if (v > mx) mx = v;
                sq += v * v;
            }
            float o = (mx + mn) * 0.5f;
            float s = (mx - mn) / 254.0f;
            if (s < 1e-20f) s = 1e-20f;
            int8_t* crow = codes + i * d;
            int32_t cs = 0;
            for (int64_t j = 0; j < d; j++) {
                float c = rintf((row[j] - o) / s);
                if (c < -127.0f) c = -127.0f;
                if (c > 127.0f) c = 127.0f;
                int32_t ci = (int32_t)c;
                crow[j] = (int8_t)ci;
                cs += ci;
            }
            scales[i] = s;
            offsets[i] = o;
            csums[i] = cs;
            sqnorms[i] = sq;
        }
    });
    return n;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// SSTable entry scans (storage/lsm.py plaintext format):
//   [u32 klen][u64 ts][u64 seq][u32 vlen][key bytes][val bytes]
// The Python per-entry struct unpacking dominated LSM reads; these scan
// in native code and hand back offsets for zero-copy value slicing.
// ---------------------------------------------------------------------------

extern "C" {

static inline int64_t ent_read(const uint8_t* buf, int64_t pos,
                               uint32_t* klen, uint64_t* ts, uint64_t* seq,
                               uint32_t* vlen) {
    memcpy(klen, buf + pos, 4);
    memcpy(ts, buf + pos + 4, 8);
    memcpy(seq, buf + pos + 12, 8);
    memcpy(vlen, buf + pos + 20, 4);
    return pos + 24;
}

static inline int keycmp(const uint8_t* a, int64_t na, const uint8_t* b,
                         int64_t nb) {
    int64_t n = na < nb ? na : nb;
    int c = memcmp(a, b, (size_t)n);
    if (c != 0) return c;
    return na < nb ? -1 : (na > nb ? 1 : 0);
}

// First entry offset with entry_key >= key, scanning from `off`.
int64_t sst_seek(const uint8_t* buf, int64_t end, int64_t off,
                 const uint8_t* key, int64_t klen) {
    int64_t pos = off;
    while (pos + 24 <= end) {
        uint32_t kl, vl; uint64_t ts, seq;
        int64_t body = ent_read(buf, pos, &kl, &ts, &seq, &vl);
        if (keycmp(buf + body, kl, key, klen) >= 0) return pos;
        pos = body + kl + vl;
    }
    return end;
}

// Versions of exactly `key` from `off` (which must be at/before the first
// match): writes (ts, seq, val_off, val_len) per version; returns count.
int64_t sst_versions(const uint8_t* buf, int64_t end, int64_t off,
                     const uint8_t* key, int64_t klen, int64_t max_out,
                     uint64_t* tss, uint64_t* seqs, int64_t* val_offs,
                     int64_t* val_lens) {
    int64_t pos = sst_seek(buf, end, off, key, klen);
    int64_t n = 0;
    while (pos + 24 <= end && n < max_out) {
        uint32_t kl, vl; uint64_t ts, seq;
        int64_t body = ent_read(buf, pos, &kl, &ts, &seq, &vl);
        if (keycmp(buf + body, kl, key, klen) != 0) break;
        tss[n] = ts;
        seqs[n] = seq;
        val_offs[n] = body + kl;
        val_lens[n] = vl;
        n++;
        pos = body + kl + vl;
    }
    return n;
}

// Entry headers from `off` while keys start with `prefix` (or all when
// prefix_len == 0): writes (key_off, key_len, ts, seq, val_off, val_len);
// returns count (callers loop with growing max_out).
// Versions of MANY sorted distinct keys in one pass. `starts[i]` is a
// seek hint at/before key i's first possible entry (sparse-index stride
// head); since keys ascend, the walk position is monotone — the scan for
// key i begins at max(current pos, starts[i]). Outputs are flattened:
// counts[i] versions for key i, written sequentially into tss/seqs/
// voffs/vlens. Returns total versions written, or -(needed) if max_out
// was too small (caller re-runs with a bigger buffer).
int64_t sst_versions_multi(const uint8_t* buf, int64_t end, int64_t nkeys,
                           const uint8_t* keys_blob, const int64_t* key_offs,
                           const int64_t* key_lens, const int64_t* starts,
                           int64_t max_out, int64_t* counts, uint64_t* tss,
                           uint64_t* seqs, int64_t* voffs, int64_t* vlens) {
    int64_t pos = 0;
    int64_t out = 0;
    for (int64_t i = 0; i < nkeys; i++) {
        const uint8_t* key = keys_blob + key_offs[i];
        int64_t klen = key_lens[i];
        if (starts[i] > pos) pos = starts[i];
        int64_t p = sst_seek(buf, end, pos, key, klen);
        int64_t n = 0;
        while (p + 24 <= end) {
            uint32_t kl, vl; uint64_t ts, seq;
            int64_t body = ent_read(buf, p, &kl, &ts, &seq, &vl);
            if (keycmp(buf + body, kl, key, klen) != 0) break;
            if (out + n >= max_out) return -(out + n + 1);
            tss[out + n] = ts;
            seqs[out + n] = seq;
            voffs[out + n] = body + kl;
            vlens[out + n] = vl;
            n++;
            p = body + kl + vl;
        }
        counts[i] = n;
        out += n;
        pos = p;
    }
    return out;
}

int64_t sst_scan(const uint8_t* buf, int64_t end, int64_t off,
                 const uint8_t* prefix, int64_t prefix_len, int64_t max_out,
                 int64_t* key_offs, int64_t* key_lens, uint64_t* tss,
                 uint64_t* seqs, int64_t* val_offs, int64_t* val_lens,
                 int64_t* next_pos) {
    int64_t pos = off;
    int64_t n = 0;
    while (pos + 24 <= end && n < max_out) {
        uint32_t kl, vl; uint64_t ts, seq;
        int64_t body = ent_read(buf, pos, &kl, &ts, &seq, &vl);
        if (prefix_len > 0) {
            if ((int64_t)kl < prefix_len ||
                memcmp(buf + body, prefix, (size_t)prefix_len) != 0) {
                break;
            }
        }
        key_offs[n] = body;
        key_lens[n] = kl;
        tss[n] = ts;
        seqs[n] = seq;
        val_offs[n] = body + kl;
        val_lens[n] = vl;
        n++;
        pos = body + kl + vl;
    }
    *next_pos = pos;
    return n;
}

// ---------------------------------------------------------------------------
// Streaming arena result encoder (query/streamjson.py): emit the bulk JSON
// row shapes — hex-uid entity arrays and count-object arrays — straight from
// the ragged level buffers into the caller's byte buffer, one call per
// contiguous run instead of one Python object per row. `pre`/`post` carry
// the constant object framing (e.g. {"uid":"0x ... "}), so one kernel
// serves every key/alias. Output formats are pinned to Python's: lowercase
// unpadded hex (hex(u) minus the 0x that rides in `pre`) and decimal int64
// (str(n)) — the byte-identity contract with json.dumps of the dict
// encoder's output lives or dies on these two formats.
// ---------------------------------------------------------------------------

static inline int64_t put_u64_hex(uint64_t v, uint8_t* out) {
    // lowercase, no leading zeros; "0" for 0 (python hex() semantics)
    static const char digits[] = "0123456789abcdef";
    if (v == 0) {
        out[0] = '0';
        return 1;
    }
    uint8_t tmp[16];
    int n = 0;
    while (v) {
        tmp[n++] = (uint8_t)digits[v & 0xF];
        v >>= 4;
    }
    for (int i = 0; i < n; i++) out[i] = tmp[n - 1 - i];
    return n;
}

static inline int64_t put_i64_dec(int64_t v, uint8_t* out) {
    uint8_t tmp[20];
    int n = 0;
    uint64_t u;
    uint8_t* p = out;
    if (v < 0) {
        *p++ = '-';
        u = (uint64_t)(-(v + 1)) + 1;  // INT64_MIN-safe negation
    } else {
        u = (uint64_t)v;
    }
    if (u == 0) tmp[n++] = '0';
    while (u) {
        tmp[n++] = (uint8_t)('0' + (u % 10));
        u /= 10;
    }
    for (int i = 0; i < n; i++) p[i] = tmp[n - 1 - i];
    return (p - out) + n;
}

// `{"uid":"0x1"},{"uid":"0x2"},...` — comma-separated, no enclosing
// brackets (the caller owns list framing). Caller sizes `out` at
// n * (pre_len + post_len + 17) bytes. Returns bytes written.
int64_t enc_uid_objs(const uint64_t* uids, int64_t n, const uint8_t* pre,
                     int64_t pre_len, const uint8_t* post, int64_t post_len,
                     uint8_t* out) {
    uint8_t* p = out;
    for (int64_t i = 0; i < n; i++) {
        if (i) *p++ = ',';
        if (pre_len) {
            memcpy(p, pre, (size_t)pre_len);
            p += pre_len;
        }
        p += put_u64_hex(uids[i], p);
        if (post_len) {
            memcpy(p, post, (size_t)post_len);
            p += post_len;
        }
    }
    return p - out;
}

// `{"c":5},{"c":3},...` — the count-leaf analog. Caller sizes `out` at
// n * (pre_len + post_len + 21) bytes. Returns bytes written.
int64_t enc_int_objs(const int64_t* vals, int64_t n, const uint8_t* pre,
                     int64_t pre_len, const uint8_t* post, int64_t post_len,
                     uint8_t* out) {
    uint8_t* p = out;
    for (int64_t i = 0; i < n; i++) {
        if (i) *p++ = ',';
        if (pre_len) {
            memcpy(p, pre, (size_t)pre_len);
            p += pre_len;
        }
        p += put_i64_dec(vals[i], p);
        if (post_len) {
            memcpy(p, post, (size_t)post_len);
            p += post_len;
        }
    }
    return p - out;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Mutation write-path kernels (posting/pl.py encode_deltas +
// tok/tok.py TermTokenizer bulk path): the live write path applied
// per-edge Python work for every posting — these move the two hottest
// loops (delta-record serialization, term tokenization) into one
// native call per transaction batch.
// ---------------------------------------------------------------------------

extern "C" {

// Batched posting-delta record encode for the fast scalar/uid shapes
// (no lang, no facets). Wire layout is posting/pl.py's, byte-exact:
//   per key:     kind u8 (=1 KIND_DELTA) | count u32 LE | postings...
//   per posting: flags u8 | uid u64 LE | value_type u8 |
//                lang_len u8 (=0) | vlen u32 LE | value bytes |
//                nfacets u16 (=0)
// Inputs are flat over all keys' postings in order; `vblob` holds the
// value bytes of value postings concatenated (vlens[j]==0 for pure uid
// edges). `out_offs` (n_keys+1) receives each key's record span in
// `out`; caller sizes `out` exactly (5 per key + 17 + vlen per
// posting). Returns total bytes written. Little-endian host assumed,
// like the bit-pack codec above.
int64_t enc_delta_records(
    const int64_t* counts, int64_t n_keys,
    const uint8_t* flags, const uint64_t* uids, const uint8_t* tids,
    const int64_t* vlens, const uint8_t* vblob,
    uint8_t* out, int64_t* out_offs) {
    uint8_t* p = out;
    int64_t j = 0;     // flat posting cursor
    int64_t voff = 0;  // value-blob cursor
    for (int64_t k = 0; k < n_keys; k++) {
        out_offs[k] = p - out;
        *p++ = 1;  // KIND_DELTA
        uint32_t cnt = (uint32_t)counts[k];
        memcpy(p, &cnt, 4);
        p += 4;
        for (int64_t c = 0; c < counts[k]; c++, j++) {
            *p++ = flags[j];
            uint64_t u = uids[j];
            memcpy(p, &u, 8);
            p += 8;
            *p++ = tids[j];
            *p++ = 0;  // lang_len
            uint32_t vl = (uint32_t)vlens[j];
            memcpy(p, &vl, 4);
            p += 4;
            if (vl) {
                memcpy(p, vblob + voff, vl);
                voff += vl;
                p += vl;
            }
            *p++ = 0;
            *p++ = 0;  // nfacets u16
        }
    }
    out_offs[n_keys] = p - out;
    return p - out;
}

// Bulk ASCII term tokenization (tok/tok.py TermTokenizer fast path):
// for each input string — caller guarantees pure ASCII; non-ASCII
// values take the Python unicode pipeline — lowercase, split into
// maximal [a-z0-9_'] runs (the `\w'` class over ASCII), dedupe,
// byte-sort, and emit each token as `prefix` byte + chars: exactly
// sorted({w for w in _word_re.findall(s.lower())}) with the
// tokenizer's identifier prefix applied. CSR output: token t spans
// out[tok_offs[t] : tok_offs[t+1]], input i owns tok_counts[i]
// consecutive tokens. Caller capacities: out >= total input bytes +
// one prefix byte per possible token; tok_offs >= 1 + sum over inputs
// of (len/2 + 1). Returns total token count.
// -------------------------------------------------------------------
// Columnar batch apply: one call turns a whole group-commit batch's
// collected edge columns into ready-to-put (key, delta-record) pairs —
// fusing data/index/reverse key construction, exact/int/bool/term
// tokenization, and posting-delta record encoding (the loops
// enc_delta_records + tok_terms_ascii each did alone, plus the Python
// key/posting assembly between them). Edge columns are flat over all
// members; member m owns edges [m_offs[m], m_offs[m+1]).
//
// Per-predicate plan (pred_ids[j] indexes it): key prefix bytes
// (x/keys.py PredicatePrefix — tag + len + ns + attr, NO kind byte; the
// kernel appends kind + suffix), pflags bits (1=reverse 2=exact 4=int
// 8=bool 16=term, mirrored in posting/colwrite.py), pidents = 4 bytes
// per pred: the exact/int/bool/term tokenizer identifier bytes.
//
// Shapes: 0 = scalar-value SET — emits the data posting
// (flags=3, uid=2^64-1, tid=vtypes[j], value=vblob slice) plus one
// index posting (flags=2, uid=entity) per plan token; 1 = list-uid SET
// — emits the data posting (flags=2, uid=objects[j]) plus the reverse
// posting (flags=2, uid=entity) under PF_REVERSE. Postings group per
// (member, key) in first-touch order, appended in edge order — the
// exact per-key append order the serial Python path produces — and
// each pair's record is pl.py encode_delta byte-exact (kind=1,
// count u32 LE, 17-byte fixed posting fields, little-endian host
// assumed like the codecs above).
//
// Outputs are CSR over pairs: key i = out_keys[out_key_offs[i]:
// out_key_offs[i+1]], record i likewise in out_recs; out_member /
// out_pred / out_kinds (0 data, 2 index, 4 reverse — x/keys.py kind
// bytes) / out_counts (postings in the record) annotate each pair.
// Caller sizes outputs from batch_apply_caps. Returns the pair count,
// or -1 if any cap would overflow (allocation bug — caps are a true
// upper bound).
// void* parameters: the Python wrapper passes raw buffer addresses
// (array.array / bytearray / bytes) — typed-pointer argtypes would
// force a ctypes cast per argument per call, which profiling showed
// dominating small-batch commits (23 pointer args on this entry).
int64_t batch_apply(
    const void* m_offs_v, int64_t n_members,
    const void* shapes_v, const void* entities_v,
    const void* pred_ids_v, const void* objects_v,
    const void* vtypes_v, const void* voffs_v, const void* vblob_v,
    const void* pp_blob_v, const void* pp_offs_v,
    const void* pflags_v, const void* pidents_v, int64_t n_preds,
    void* out_keys_v, void* out_key_offs_v,
    void* out_recs_v, void* out_rec_offs_v,
    void* out_member_v, void* out_pred_v, void* out_kinds_v,
    void* out_counts_v, int64_t max_pairs) {
    (void)n_preds;
    const int64_t* m_offs = (const int64_t*)m_offs_v;
    const uint8_t* shapes = (const uint8_t*)shapes_v;
    const uint64_t* entities = (const uint64_t*)entities_v;
    const int32_t* pred_ids = (const int32_t*)pred_ids_v;
    const uint64_t* objects = (const uint64_t*)objects_v;
    const uint8_t* vtypes = (const uint8_t*)vtypes_v;
    const int64_t* voffs = (const int64_t*)voffs_v;
    const uint8_t* vblob = (const uint8_t*)vblob_v;
    const uint8_t* pp_blob = (const uint8_t*)pp_blob_v;
    const int64_t* pp_offs = (const int64_t*)pp_offs_v;
    const uint8_t* pflags = (const uint8_t*)pflags_v;
    const uint8_t* pidents = (const uint8_t*)pidents_v;
    uint8_t* out_keys = (uint8_t*)out_keys_v;
    int64_t* out_key_offs = (int64_t*)out_key_offs_v;
    uint8_t* out_recs = (uint8_t*)out_recs_v;
    int64_t* out_rec_offs = (int64_t*)out_rec_offs_v;
    int32_t* out_member = (int32_t*)out_member_v;
    int32_t* out_pred = (int32_t*)out_pred_v;
    uint8_t* out_kinds = (uint8_t*)out_kinds_v;
    int32_t* out_counts = (int32_t*)out_counts_v;
    struct Slot {
        std::string key;
        std::string posts;  // posting bytes (record body)
        int32_t count = 0;
        int32_t pred = 0;
        uint8_t kind = 0;
    };
    int64_t npairs = 0;
    int64_t key_w = 0, rec_w = 0;
    std::vector<Slot> slots;
    std::unordered_map<std::string, size_t> by_key;
    std::string kbuf;
    std::vector<uint8_t> low;
    std::vector<std::pair<int64_t, int64_t>> words;
    auto post17 = [](std::string& dst, uint8_t flags, uint64_t uid,
                     uint8_t tid, const uint8_t* val, uint32_t vlen) {
        char hdr[15];
        hdr[0] = (char)flags;
        memcpy(hdr + 1, &uid, 8);
        hdr[9] = (char)tid;
        hdr[10] = 0;  // lang_len
        memcpy(hdr + 11, &vlen, 4);
        dst.append(hdr, 15);
        if (vlen) dst.append((const char*)val, vlen);
        dst.push_back(0);
        dst.push_back(0);  // nfacets u16
    };
    for (int64_t m = 0; m < n_members; m++) {
        slots.clear();
        by_key.clear();
        auto touch = [&](const std::string& key, int32_t pred,
                         uint8_t kind) -> Slot& {
            auto it = by_key.find(key);
            if (it == by_key.end()) {
                it = by_key.emplace(key, slots.size()).first;
                slots.emplace_back();
                slots.back().key = key;
                slots.back().pred = pred;
                slots.back().kind = kind;
            }
            return slots[it->second];
        };
        for (int64_t j = m_offs[m]; j < m_offs[m + 1]; j++) {
            int32_t pid = pred_ids[j];
            const uint8_t* pp = pp_blob + pp_offs[pid];
            size_t pplen = (size_t)(pp_offs[pid + 1] - pp_offs[pid]);
            uint8_t pf = pflags[pid];
            const uint8_t* idents = pidents + 4 * pid;
            uint64_t ent = entities[j];
            if (shapes[j] == 0) {
                const uint8_t* val = vblob + voffs[j];
                uint32_t vlen = (uint32_t)(voffs[j + 1] - voffs[j]);
                // data key: prefix | 0x00 | uid u64 BE
                kbuf.assign((const char*)pp, pplen);
                kbuf.push_back((char)0x00);
                for (int b = 7; b >= 0; b--)
                    kbuf.push_back((char)((ent >> (8 * b)) & 0xff));
                Slot& ds = touch(kbuf, pid, 0x00);
                post17(ds.posts, 3, ~0ULL, vtypes[j], val, vlen);
                ds.count++;
                auto index_post = [&](const std::string& key) {
                    Slot& is = touch(key, pid, 0x02);
                    post17(is.posts, 2, ent, 0, nullptr, 0);
                    is.count++;
                };
                if (pf & 2) {  // exact: ident + value bytes
                    kbuf.assign((const char*)pp, pplen);
                    kbuf.push_back((char)0x02);
                    kbuf.push_back((char)idents[0]);
                    kbuf.append((const char*)val, vlen);
                    index_post(kbuf);
                }
                if (pf & 4) {  // int: ident + BE64(LE i64 + 2^63)
                    int64_t iv;
                    memcpy(&iv, val, 8);
                    uint64_t biased = (uint64_t)iv + (1ULL << 63);
                    kbuf.assign((const char*)pp, pplen);
                    kbuf.push_back((char)0x02);
                    kbuf.push_back((char)idents[1]);
                    for (int b = 7; b >= 0; b--)
                        kbuf.push_back(
                            (char)((biased >> (8 * b)) & 0xff));
                    index_post(kbuf);
                }
                if (pf & 8) {  // bool: ident + stored byte
                    kbuf.assign((const char*)pp, pplen);
                    kbuf.push_back((char)0x02);
                    kbuf.push_back((char)idents[2]);
                    kbuf.push_back((char)(val[0] ? 1 : 0));
                    index_post(kbuf);
                }
                if (pf & 16) {  // term: tok_terms_ascii's algorithm
                    low.resize(vlen);
                    for (uint32_t c = 0; c < vlen; c++) {
                        uint8_t ch = val[c];
                        low[c] = (ch >= 'A' && ch <= 'Z')
                                     ? (uint8_t)(ch + 32)
                                     : ch;
                    }
                    words.clear();
                    int64_t start = -1;
                    for (int64_t c = 0; c <= (int64_t)vlen; c++) {
                        uint8_t ch = c < (int64_t)vlen ? low[(size_t)c]
                                                       : 0;
                        bool w = (ch >= 'a' && ch <= 'z') ||
                                 (ch >= '0' && ch <= '9') ||
                                 ch == '_' || ch == '\'';
                        if (w && start < 0) start = c;
                        if (!w && start >= 0) {
                            words.emplace_back(start, c - start);
                            start = -1;
                        }
                    }
                    const uint8_t* lo = low.data();
                    std::sort(
                        words.begin(), words.end(),
                        [lo](const std::pair<int64_t, int64_t>& a,
                             const std::pair<int64_t, int64_t>& b) {
                            int64_t mn = a.second < b.second
                                             ? a.second
                                             : b.second;
                            int c = memcmp(lo + a.first, lo + b.first,
                                           (size_t)mn);
                            if (c) return c < 0;
                            return a.second < b.second;
                        });
                    for (size_t wi = 0; wi < words.size(); wi++) {
                        if (wi > 0 &&
                            words[wi].second == words[wi - 1].second &&
                            memcmp(lo + words[wi].first,
                                   lo + words[wi - 1].first,
                                   (size_t)words[wi].second) == 0)
                            continue;  // duplicate word
                        kbuf.assign((const char*)pp, pplen);
                        kbuf.push_back((char)0x02);
                        kbuf.push_back((char)idents[3]);
                        kbuf.append((const char*)(lo + words[wi].first),
                                    (size_t)words[wi].second);
                        index_post(kbuf);
                    }
                }
            } else {
                uint64_t obj = objects[j];
                kbuf.assign((const char*)pp, pplen);
                kbuf.push_back((char)0x00);
                for (int b = 7; b >= 0; b--)
                    kbuf.push_back((char)((ent >> (8 * b)) & 0xff));
                Slot& ds = touch(kbuf, pid, 0x00);
                post17(ds.posts, 2, obj, 0, nullptr, 0);
                ds.count++;
                if (pf & 1) {  // reverse: prefix | 0x04 | object BE
                    kbuf.assign((const char*)pp, pplen);
                    kbuf.push_back((char)0x04);
                    for (int b = 7; b >= 0; b--)
                        kbuf.push_back((char)((obj >> (8 * b)) & 0xff));
                    Slot& rs = touch(kbuf, pid, 0x04);
                    post17(rs.posts, 2, ent, 0, nullptr, 0);
                    rs.count++;
                }
            }
        }
        // flush this member's pairs in first-touch order
        for (const Slot& s : slots) {
            if (npairs >= max_pairs) return -1;
            out_key_offs[npairs] = key_w;
            out_rec_offs[npairs] = rec_w;
            memcpy(out_keys + key_w, s.key.data(), s.key.size());
            key_w += (int64_t)s.key.size();
            out_recs[rec_w] = 1;  // KIND_DELTA
            uint32_t cnt = (uint32_t)s.count;
            memcpy(out_recs + rec_w + 1, &cnt, 4);
            memcpy(out_recs + rec_w + 5, s.posts.data(),
                   s.posts.size());
            rec_w += 5 + (int64_t)s.posts.size();
            out_member[npairs] = (int32_t)m;
            out_pred[npairs] = s.pred;
            out_kinds[npairs] = s.kind;
            out_counts[npairs] = s.count;
            npairs++;
        }
    }
    out_key_offs[npairs] = key_w;
    out_rec_offs[npairs] = rec_w;
    return npairs;
}

// Output-capacity upper bounds for batch_apply over the same columns:
// caps[0] = pair count, caps[1] = key bytes, caps[2] = record bytes.
// Term tokens are bounded by len/2 + 1 words of the value; everything
// else is exact. Returns caps[0].
int64_t batch_apply_caps(
    const void* m_offs_v, int64_t n_members, const void* shapes_v,
    const void* pred_ids_v, const void* voffs_v,
    const void* pp_offs_v, const void* pflags_v, int64_t n_preds,
    void* caps_v) {
    (void)n_preds;
    const int64_t* m_offs = (const int64_t*)m_offs_v;
    const uint8_t* shapes = (const uint8_t*)shapes_v;
    const int32_t* pred_ids = (const int32_t*)pred_ids_v;
    const int64_t* voffs = (const int64_t*)voffs_v;
    const int64_t* pp_offs = (const int64_t*)pp_offs_v;
    const uint8_t* pflags = (const uint8_t*)pflags_v;
    int64_t* caps = (int64_t*)caps_v;
    int64_t pairs = 0, keyb = 0, posts = 0, valb = 0;
    for (int64_t j = 0; j < m_offs[n_members]; j++) {
        int32_t pid = pred_ids[j];
        int64_t pplen = pp_offs[pid + 1] - pp_offs[pid];
        int64_t vlen = voffs[j + 1] - voffs[j];
        uint8_t pf = pflags[pid];
        pairs++;  // data pair
        keyb += pplen + 9;
        posts++;
        if (shapes[j] == 0) {
            valb += vlen;
            if (pf & 2) {
                pairs++;
                keyb += pplen + 2 + vlen;
                posts++;
            }
            if (pf & 4) {
                pairs++;
                keyb += pplen + 10;
                posts++;
            }
            if (pf & 8) {
                pairs++;
                keyb += pplen + 3;
                posts++;
            }
            if (pf & 16) {
                int64_t ntok = vlen / 2 + 1;
                pairs += ntok;
                keyb += ntok * (pplen + 2) + vlen;
                posts += ntok;
            }
        } else if (pf & 1) {
            pairs++;
            keyb += pplen + 9;
            posts++;
        }
    }
    caps[0] = pairs;
    caps[1] = keyb;
    caps[2] = 5 * pairs + 17 * posts + valb;
    return pairs;
}

int64_t tok_terms_ascii(
    const uint8_t* blob, const int64_t* offs, int64_t n, int prefix,
    uint8_t* out, int64_t* tok_offs, int64_t* tok_counts) {
    int64_t ntok = 0;
    uint8_t* p = out;
    tok_offs[0] = 0;
    std::vector<uint8_t> low;
    std::vector<std::pair<int64_t, int64_t>> words;  // (start, len)
    for (int64_t i = 0; i < n; i++) {
        const uint8_t* s = blob + offs[i];
        int64_t len = offs[i + 1] - offs[i];
        low.resize((size_t)len);
        for (int64_t c = 0; c < len; c++) {
            uint8_t ch = s[c];
            low[(size_t)c] =
                (ch >= 'A' && ch <= 'Z') ? (uint8_t)(ch + 32) : ch;
        }
        words.clear();
        int64_t start = -1;
        for (int64_t c = 0; c <= len; c++) {
            uint8_t ch = c < len ? low[(size_t)c] : 0;
            bool w = (ch >= 'a' && ch <= 'z') || (ch >= '0' && ch <= '9')
                  || ch == '_' || ch == '\'';
            if (w && start < 0) start = c;
            if (!w && start >= 0) {
                words.emplace_back(start, c - start);
                start = -1;
            }
        }
        const uint8_t* lo = low.data();
        std::sort(words.begin(), words.end(),
                  [lo](const std::pair<int64_t, int64_t>& a,
                       const std::pair<int64_t, int64_t>& b) {
                      int64_t m = a.second < b.second ? a.second : b.second;
                      int c = memcmp(lo + a.first, lo + b.first, (size_t)m);
                      if (c) return c < 0;
                      return a.second < b.second;
                  });
        int64_t emitted = 0;
        for (size_t wi = 0; wi < words.size(); wi++) {
            if (wi > 0 && words[wi].second == words[wi - 1].second &&
                memcmp(lo + words[wi].first, lo + words[wi - 1].first,
                       (size_t)words[wi].second) == 0)
                continue;  // duplicate word
            *p++ = (uint8_t)prefix;
            memcpy(p, lo + words[wi].first, (size_t)words[wi].second);
            p += words[wi].second;
            ntok++;
            emitted++;
            tok_offs[ntok] = p - out;
        }
        tok_counts[i] = emitted;
    }
    return ntok;
}

}  // extern "C"

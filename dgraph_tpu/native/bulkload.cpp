// Native bulk-load pipeline: the map/shuffle/reduce hot path of
// loaders/bulk2.py (ref dgraph/cmd/bulk loader.go mapStage/reduceStage)
// in C++. The Python orchestrator owns schema, xid lease, storage
// ingest and every uncommon line shape (facets, @lang, typed literals,
// non-ASCII, exotic tokenizers) — those lines are returned as "slow"
// text and run through the Python mapper into the same run format, so
// the native reduce merges both.
//
// Byte formats replicated EXACTLY (shared storage formats):
//   keys:     x/keys.py        [tag][len u16 BE][ns u64 BE + attr][kind][suffix]
//   runs:     loaders/bulk2.py  _REC = <HBI> klen kind plen
//   postings: posting/pl.py    _enc_posting wire layout
//   uid pack: codec/uidpack.py serialize_uids (magic UPK1, bitpacked)
//   tokens:   tok/tok.py       ident-byte-prefixed token bytes
//   farmhash: utils/farmhash.py Fingerprint64 (public FarmHash spec)

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>
#include <map>
#include <queue>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

using u8 = uint8_t;
using u16 = uint16_t;
using u32 = uint32_t;
using u64 = uint64_t;
using i64 = int64_t;

// ---------------------------------------------------------------------------
// FarmHash Fingerprint64 (port of utils/farmhash.py, public spec)
// ---------------------------------------------------------------------------

constexpr u64 K0 = 0xC3A5C85C97CB3127ULL;
constexpr u64 K1 = 0xB492B66FBE98F273ULL;
constexpr u64 K2 = 0x9AE16A3B2F90404FULL;

static inline u64 rot(u64 v, int s) { return s == 0 ? v : (v >> s) | (v << (64 - s)); }
static inline u64 smix(u64 v) { return v ^ (v >> 47); }
static inline u64 f64(const u8* s, size_t i) { u64 v; memcpy(&v, s + i, 8); return v; }
static inline u64 f32(const u8* s, size_t i) { u32 v; memcpy(&v, s + i, 4); return v; }

static u64 h16(u64 u, u64 v, u64 mul) {
  u64 a = (u ^ v) * mul; a ^= a >> 47;
  u64 b = (v ^ a) * mul; b ^= b >> 47;
  return b * mul;
}

static u64 len0to16(const u8* s, size_t n) {
  if (n >= 8) {
    u64 mul = K2 + n * 2;
    u64 a = f64(s, 0) + K2, b = f64(s, n - 8);
    u64 c = rot(b, 37) * mul + a, d = (rot(a, 25) + b) * mul;
    return h16(c, d, mul);
  }
  if (n >= 4) {
    u64 mul = K2 + n * 2, a = f32(s, 0);
    return h16(n + (a << 3), f32(s, n - 4), mul);
  }
  if (n > 0) {
    u64 a = s[0], b = s[n >> 1], c = s[n - 1];
    u64 y = a + (b << 8), z = n + (c << 2);
    return smix(y * K2 ^ z * K0) * K2;
  }
  return K2;
}

static u64 len17to32(const u8* s, size_t n) {
  u64 mul = K2 + n * 2;
  u64 a = f64(s, 0) * K1, b = f64(s, 8);
  u64 c = f64(s, n - 8) * mul, d = f64(s, n - 16) * K2;
  return h16(rot(a + b, 43) + rot(c, 30) + d, a + rot(b + K2, 18) + c, mul);
}

static u64 len33to64(const u8* s, size_t n) {
  u64 mul = K2 + n * 2;
  u64 a = f64(s, 0) * K2, b = f64(s, 8);
  u64 c = f64(s, n - 8) * mul, d = f64(s, n - 16) * K2;
  u64 y = rot(a + b, 43) + rot(c, 30) + d;
  u64 z = h16(y, a + rot(b + K2, 18) + c, mul);
  u64 e = f64(s, 16) * mul, f = f64(s, 24);
  u64 g = (y + f64(s, n - 32)) * mul, h = (z + f64(s, n - 24)) * mul;
  return h16(rot(e + f, 43) + rot(g, 30) + h, e + rot(f + a, 18) + g, mul);
}

static void weak32(const u8* s, size_t i, u64 a, u64 b, u64* oa, u64* ob) {
  u64 w = f64(s, i), x = f64(s, i + 8), y = f64(s, i + 16), z = f64(s, i + 24);
  a += w;
  b = rot(b + a + z, 21);
  u64 c = a;
  a += x + y;
  b += rot(a, 44);
  *oa = a + z;
  *ob = b + c;
}

static u64 farm64(const u8* s, size_t n) {
  if (n <= 16) return len0to16(s, n);
  if (n <= 32) return len17to32(s, n);
  if (n <= 64) return len33to64(s, n);
  u64 seed = 81;
  u64 x = seed, y = seed * K1 + 113;
  u64 z = smix(y * K2 + 113) * K2;
  u64 v1 = 0, v2 = 0, w1 = 0, w2 = 0;
  x = x * K2 + f64(s, 0);
  size_t end = ((n - 1) / 64) * 64, last64 = n - 64, i = 0;
  while (i < end) {
    x = rot(x + y + v1 + f64(s, i + 8), 37) * K1;
    y = rot(y + v2 + f64(s, i + 48), 42) * K1;
    x ^= w2;
    y = y + v1 + f64(s, i + 40);
    z = rot(z + w1, 33) * K1;
    weak32(s, i, v2 * K1, x + w1, &v1, &v2);
    weak32(s, i + 32, z + w2, y + f64(s, i + 16), &w1, &w2);
    std::swap(z, x);
    i += 64;
  }
  u64 mul = K1 + ((z & 0xFF) << 1);
  i = last64;
  w1 += (n - 1) & 63;
  v1 += w1;
  w1 += v1;
  x = rot(x + y + v1 + f64(s, i + 8), 37) * mul;
  y = rot(y + v2 + f64(s, i + 48), 42) * mul;
  x ^= w2 * 9;
  y = y + v1 * 9 + f64(s, i + 40);
  z = rot(z + w1, 33) * mul;
  weak32(s, i, v2 * mul, x + w1, &v1, &v2);
  weak32(s, i + 32, z + w2, y + f64(s, i + 16), &w1, &w2);
  std::swap(z, x);
  return h16(h16(v1, w1, mul) + smix(y) * K0 + z, h16(v2, w2, mul) + x, mul);
}

// ---------------------------------------------------------------------------
// Schema / value plumbing
// ---------------------------------------------------------------------------

// TypeID values (types/types.py)
enum { T_DEFAULT = 0, T_BINARY = 1, T_INT = 2, T_FLOAT = 3, T_BOOL = 4,
       T_DATETIME = 5, T_GEO = 6, T_UID = 7, T_STRING = 9 };

// tokenizer identifier bytes (tok/tok.py)
enum { TOK_TERM = 0x1, TOK_EXACT = 0x2, TOK_YEAR = 0x4, TOK_MONTH = 0x41,
       TOK_DAY = 0x42, TOK_HOUR = 0x43, TOK_INT = 0x6, TOK_FLOAT = 0x7,
       TOK_FULLTEXT = 0x8, TOK_BOOL = 0x9 };

constexpr u64 VALUE_UID = ~0ULL;
constexpr u8 OP_SET = 1;
constexpr u8 K_UID = 0, K_VAL = 1, K_IDX = 2;

struct Pred {
  u8 value_type = T_DEFAULT;
  bool is_list = false, reverse = false, count = false, has_lang = false;
  std::vector<u8> toks;  // supported tokenizer ids only
  std::string data_prefix, rev_prefix, idx_prefix;  // precomputed key heads
};

static void put_u16be(std::string& o, u16 v) { o.push_back(char(v >> 8)); o.push_back(char(v & 0xFF)); }
static void put_u64be(std::string& o, u64 v) { for (int i = 7; i >= 0; --i) o.push_back(char((v >> (8 * i)) & 0xFF)); }
static void put_u32le(std::string& o, u32 v) { o.append((const char*)&v, 4); }
static void put_u64le(std::string& o, u64 v) { o.append((const char*)&v, 8); }

// key head: [0x00][len u16 BE][ns u64 BE + attr] + kind byte
static std::string key_head(u64 ns, const std::string& attr, u8 kind) {
  std::string o;
  o.push_back('\x00');
  put_u16be(o, u16(8 + attr.size()));
  put_u64be(o, ns);
  o += attr;
  o.push_back(char(kind));
  return o;
}

struct Entry {
  std::string key;
  u8 kind;
  std::string payload;
  bool operator<(const Entry& b) const {
    if (key != b.key) return key < b.key;
    if (kind != b.kind) return kind < b.kind;
    return payload < b.payload;
  }
};

struct Ctx {
  std::unordered_map<std::string, u64> xids;
  std::vector<std::string> xid_order;  // sorted, for assignment
  u64 base = 0;
  std::unordered_map<std::string, Pred> preds;
  u64 nquads = 0;
  std::vector<std::string> runs;
  std::string err;
};

// ---------------------------------------------------------------------------
// Value conversion + posting/token emission
// ---------------------------------------------------------------------------

struct DT { int y=0, mo=1, d=1, h=0, mi=0, s=0; long micro=0; bool tz=false; int tzmin=0; };

static bool parse_dt(const char* p, size_t n, DT* o) {
  // YYYY[-MM[-DD[THH:MM:SS[.ffffff][Z|+HH:MM]]]]
  auto num = [&](size_t i, size_t len, int* out) {
    int v = 0;
    for (size_t k = i; k < i + len; ++k) {
      if (k >= n || p[k] < '0' || p[k] > '9') return false;
      v = v * 10 + (p[k] - '0');
    }
    *out = v;
    return true;
  };
  if (!num(0, 4, &o->y)) return false;
  size_t i = 4;
  if (i == n) return true;
  if (p[i] != '-' || !num(i + 1, 2, &o->mo)) return false;
  i += 3;
  if (i == n) return true;
  if (p[i] != '-' || !num(i + 1, 2, &o->d)) return false;
  i += 3;
  if (i == n) return true;
  if (p[i] != 'T' && p[i] != ' ') return false;
  if (!num(i + 1, 2, &o->h)) return false;
  if (p[i + 3] != ':' || !num(i + 4, 2, &o->mi)) return false;
  if (p[i + 6] != ':' || !num(i + 7, 2, &o->s)) return false;
  i += 9;
  if (i < n && p[i] == '.') {
    size_t j = i + 1; long frac = 0; int digits = 0;
    while (j < n && p[j] >= '0' && p[j] <= '9' && digits < 9) {
      frac = frac * 10 + (p[j] - '0'); ++digits; ++j;
    }
    while (digits < 6) { frac *= 10; ++digits; }
    while (digits > 6) { frac /= 10; --digits; }
    o->micro = frac;
    i = j;
  }
  if (i == n) return true;
  if (p[i] == 'Z' && i + 1 == n) { o->tz = true; o->tzmin = 0; return true; }
  if ((p[i] == '+' || p[i] == '-') && i + 6 == n) {
    int hh, mm;
    if (!num(i + 1, 2, &hh) || p[i + 3] != ':' || !num(i + 4, 2, &mm)) return false;
    o->tz = true;
    o->tzmin = (hh * 60 + mm) * (p[i] == '-' ? -1 : 1);
    return true;
  }
  return false;
}

// matches datetime.isoformat() of parse_datetime(s)
static std::string dt_isoformat(const DT& d) {
  char buf[64];
  int len = snprintf(buf, sizeof buf, "%04d-%02d-%02dT%02d:%02d:%02d",
                     d.y, d.mo, d.d, d.h, d.mi, d.s);
  std::string o(buf, len);
  if (d.micro) {
    len = snprintf(buf, sizeof buf, ".%06ld", d.micro);
    o.append(buf, len);
  }
  if (d.tz) {
    int m = d.tzmin, am = m < 0 ? -m : m;
    len = snprintf(buf, sizeof buf, "%c%02d:%02d", m < 0 ? '-' : '+', am / 60, am % 60);
    o.append(buf, len);
  }
  return o;
}

static i64 days_from_civil(int y, int m, int d) {
  y -= m <= 2;
  i64 era = (y >= 0 ? y : y - 399) / 400;
  unsigned yoe = unsigned(y - era * 400);
  unsigned doy = (153 * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1;
  unsigned doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
  return era * 146097 + i64(doe) - 719468;  // days since 1970-01-01
}

// Go time.MarshalBinary v1 (utils/farmhash.py go_time_binary)
static std::string go_time_binary(const DT& d) {
  i64 unix_s = days_from_civil(d.y, d.mo, d.d) * 86400 + d.h * 3600 + d.mi * 60 + d.s;
  int offmin;
  if (!d.tz) offmin = -1;
  else { unix_s -= i64(d.tzmin) * 60; offmin = d.tzmin == 0 ? -1 : d.tzmin; }
  // RFC3339 "+00:00"/"Z" parse to the UTC singleton in Python => -1
  const i64 UNIX_TO_INTERNAL = (1969LL * 365 + 1969 / 4 - 1969 / 100 + 1969 / 400) * 86400;
  i64 sec = unix_s + UNIX_TO_INTERNAL;
  i64 nsec = d.micro * 1000;
  std::string o;
  o.push_back('\x01');
  put_u64be(o, u64(sec));
  o.push_back(char((nsec >> 24) & 0xFF)); o.push_back(char((nsec >> 16) & 0xFF));
  o.push_back(char((nsec >> 8) & 0xFF)); o.push_back(char(nsec & 0xFF));
  o.push_back(char((offmin >> 8) & 0xFF)); o.push_back(char(offmin & 0xFF));
  return o;
}

// sortable int token payload (tok.py _enc_int_sortable)
static std::string enc_int_sortable(i64 x) {
  std::string o;
  put_u64be(o, u64(x) + 0x8000000000000000ULL);
  return o;
}

static const char* STOPWORDS[] = {
  "a","an","and","are","as","at","be","by","for","from","has","he","in","is",
  "it","its","of","on","that","the","to","was","were","will","with","this",
  "those","these","you","your","i","we","they","them","he","she","our","not",
  "no","or","but","if","then","so","what","which","who","whom", nullptr};

static bool is_stopword(const std::string& w) {
  for (int i = 0; STOPWORDS[i]; ++i)
    if (w == STOPWORDS[i]) return true;
  return false;
}

// tok.py _porter_stem (tiny suffix stripper)
static std::string porter_stem(std::string w) {
  static const char* SUF[] = {"ingly","edly","ing","ed","ly","ies","es","s", nullptr};
  for (int i = 0; SUF[i]; ++i) {
    size_t sl = strlen(SUF[i]);
    if (w.size() >= sl && w.size() - sl >= 3 &&
        w.compare(w.size() - sl, sl, SUF[i]) == 0) {
      w.resize(w.size() - sl);
      if (strcmp(SUF[i], "ies") == 0) w += "y";
      break;
    }
  }
  return w;
}

// ASCII word split + lowercase ([\w']+ on pre-checked ASCII text)
static std::vector<std::string> words_ascii(const char* p, size_t n) {
  std::vector<std::string> out;
  std::string cur;
  for (size_t i = 0; i < n; ++i) {
    char c = p[i];
    bool wc = (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
              (c >= '0' && c <= '9') || c == '_' || c == '\'';
    if (wc) cur.push_back(c >= 'A' && c <= 'Z' ? c + 32 : c);
    else if (!cur.empty()) { out.push_back(cur); cur.clear(); }
  }
  if (!cur.empty()) out.push_back(cur);
  return out;
}

// posting/pl.py _enc_posting: fast-path value posting (no lang/facets)
static std::string enc_value_posting(u64 puid, u8 tid, const std::string& v) {
  std::string o;
  o.push_back(char(1 | (OP_SET << 1)));
  put_u64le(o, puid);
  o.push_back(char(tid));
  o.push_back('\x00');            // lang len
  put_u32le(o, u32(v.size()));
  o += v;
  o.push_back('\x00'); o.push_back('\x00');  // facet count u16
  return o;
}

// ---------------------------------------------------------------------------
// uid pack serialization (codec/uidpack.py serialize_uids / serialize)
// ---------------------------------------------------------------------------

static int width_bits(const u32* v, size_t n) {
  u32 mx = 0;
  for (size_t i = 0; i < n; ++i) mx = std::max(mx, v[i]);
  int w = 0;
  while ((1ULL << w) <= mx) ++w;  // bit_length of max
  return mx == 0 ? 0 : w;
}

static void bitpack_into(const u32* vals, size_t n, int width, std::string& out) {
  if (width == 0 || n == 0) return;
  size_t nbytes = (n * width + 7) / 8;
  size_t start = out.size();
  out.resize(start + nbytes, 0);
  u8* buf = (u8*)out.data() + start;
  size_t bit = 0;
  for (size_t i = 0; i < n; ++i) {
    u64 v = vals[i];
    size_t byte = bit >> 3;
    int sh = bit & 7;
    u64 cur = v << sh;
    for (int b = 0; b < 5 && byte + b < nbytes; ++b)
      buf[byte + b] |= u8((cur >> (8 * b)) & 0xFF);
    bit += width;
  }
}

static void serialize_uids(const std::vector<u64>& u, std::string& out) {
  out += "UPK1";
  size_t n = u.size();
  if (n == 0) { put_u64le(out, 0); put_u32le(out, 0); return; }
  // block split: <=256 per block, never spanning a hi-32 boundary
  std::vector<std::pair<size_t, size_t>> blocks;  // (start, count)
  size_t i = 0;
  while (i < n) {
    size_t j = i + 1;
    u64 hi = u[i] >> 32;
    while (j < n && j - i < 256 && (u[j] >> 32) == hi) ++j;
    blocks.emplace_back(i, j - i);
    i = j;
  }
  put_u64le(out, u64(n));
  put_u32le(out, u32(blocks.size()));
  std::vector<u32> offs;
  for (auto& b : blocks) {
    offs.clear();
    u64 base = u[b.first];
    for (size_t k = 0; k < b.second; ++k) offs.push_back(u32(u[b.first + k] - base));
    int w = width_bits(offs.data(), offs.size());
    put_u64le(out, base);
    out.push_back(char(b.second & 0xFF)); out.push_back(char((b.second >> 8) & 0xFF));
    out.push_back(char(w));
    bitpack_into(offs.data(), offs.size(), w, out);
  }
}

// posting/pl.py encode_rollup
static void encode_rollup(const std::string& pack,
                          const std::vector<const std::string*>& posts,
                          const std::vector<u64>& splits, std::string& out) {
  out.push_back('\x00');  // KIND_ROLLUP
  put_u32le(out, u32(pack.size()));
  out += pack;
  put_u32le(out, u32(posts.size()));
  for (auto* p : posts) out += *p;
  put_u32le(out, u32(splits.size()));
  for (u64 s : splits) put_u64le(out, s);
}

// x/keys.py SplitKey: [0x03] + base_key[1:] + [start u64 BE]
static std::string split_key(const std::string& main, u64 start) {
  std::string o;
  o.push_back('\x03');
  o.append(main, 1, main.size() - 1);
  put_u64be(o, start);
  return o;
}

// ---------------------------------------------------------------------------
// Map phase
// ---------------------------------------------------------------------------

struct MapState {
  std::vector<Entry> entries;
  size_t spill_at;
  Ctx* ctx;
  std::string workdir;
  int run_no = 0;
  FILE* slow = nullptr;

  void spill() {
    if (entries.empty()) return;
    std::sort(entries.begin(), entries.end());
    char path[4096];
    snprintf(path, sizeof path, "%s/native_%04d.map", workdir.c_str(), run_no++);
    FILE* f = fopen(path, "wb");
    if (!f) { ctx->err = "cannot open run file"; return; }
    std::string buf;
    buf.reserve(1 << 22);
    for (auto& e : entries) {
      u16 kl = u16(e.key.size());
      u32 pl = u32(e.payload.size());
      char hdr[7];
      memcpy(hdr, &kl, 2); hdr[2] = char(e.kind); memcpy(hdr + 3, &pl, 4);
      buf.append(hdr, 7);
      buf += e.key;
      buf += e.payload;
      if (buf.size() > (1 << 22)) { fwrite(buf.data(), 1, buf.size(), f); buf.clear(); }
    }
    if (!buf.empty()) fwrite(buf.data(), 1, buf.size(), f);
    fclose(f);
    ctx->runs.push_back(path);
    entries.clear();
  }

  void add(std::string key, u8 kind, std::string payload) {
    entries.push_back({std::move(key), kind, std::move(payload)});
    if (entries.size() >= spill_at) spill();
  }
};

static bool resolve_ref(Ctx* c, const char* p, size_t n, u64* out) {
  if (n > 2 && p[0] == '0' && p[1] == 'x') {
    *out = strtoull(std::string(p, n).c_str(), nullptr, 16);
    return true;
  }
  bool digits = n > 0;
  for (size_t i = 0; i < n; ++i) if (p[i] < '0' || p[i] > '9') { digits = false; break; }
  if (digits) { *out = strtoull(std::string(p, n).c_str(), nullptr, 10); return true; }
  auto it = c->xids.find(std::string(p, n));
  if (it == c->xids.end()) return false;
  *out = it->second;
  return true;
}

// one fast line:  <s> <p> <o> .   |   <s> <p> "literal" .
// Returns false for anything else (or any byte >= 0x80): slow path.
static bool try_fast_line(Ctx* c, MapState* st, const char* p, size_t n,
                          u64 ns) {
  (void)ns;
  for (size_t i = 0; i < n; ++i)
    if ((u8)p[i] >= 0x80) return false;
  {
    if (p[0] != '<') return false;
    const char* se = (const char*)memchr(p + 1, '>', n - 1);
    if (!se) return false;
    size_t si = se - p;           // index of '>'
    size_t i = si + 1;
    while (i < n && p[i] == ' ') ++i;
    if (i >= n || p[i] != '<') return false;
    const char* pe = (const char*)memchr(p + i + 1, '>', n - i - 1);
    if (!pe) return false;
    size_t pstart = i + 1, pend = pe - p;
    i = pend + 1;
    while (i < n && p[i] == ' ') ++i;
    if (i >= n) return false;
    // must end with " ." / "."
    size_t e = n;
    if (p[e - 1] != '.') return false;
    --e;
    while (e > i && (p[e - 1] == ' ' || p[e - 1] == '\t')) --e;

    std::string attr(p + pstart, pend - pstart);
    auto pit = c->preds.find(attr);
    if (pit == c->preds.end()) return false;  // undeclared: Python infers
    Pred& pr = pit->second;

    u64 subj;
    if (!resolve_ref(c, p + 1, si - 1, &subj)) return false;

    if (p[i] == '<') {
      // uid edge
      const char* oe = (const char*)memchr(p + i + 1, '>', e - i - 1);
      if (!oe || size_t(oe - p) != e - 1) return false;
      u64 obj;
      if (!resolve_ref(c, p + i + 1, oe - p - i - 1, &obj)) return false;
      std::string dk = pr.data_prefix;
      put_u64be(dk, subj);
      std::string pay;
      pay.reserve(8);
      { u64 o = obj; pay.append((const char*)&o, 8); }
      st->add(std::move(dk), K_UID, std::move(pay));
      if (pr.reverse) {
        std::string rk = pr.rev_prefix;
        put_u64be(rk, obj);
        std::string pay2;
        { u64 o = subj; pay2.append((const char*)&o, 8); }
        st->add(std::move(rk), K_UID, std::move(pay2));
      }
      ++c->nquads;
      return true;
    }
    if (p[i] != '"') return false;
    // find closing quote (no escapes in the fast path)
    const char* lit = p + i + 1;
    const char* q = (const char*)memchr(lit, '"', e - i - 1);
    if (!q) return false;
    size_t ln = q - lit;
    for (size_t k = 0; k < ln; ++k)
      if (lit[k] == '\\') return false;
    size_t after = (q - p) + 1;
    if (after != e) {
      // optional ^^<dtype>: accepted only when the dtype maps to the
      // SCHEMA's own type (then text->type conversion is identical to
      // the Python parse+convert chain); anything else is slow
      if (after + 2 > e || p[after] != '^' || p[after + 1] != '^' ||
          p[after + 2] != '<' || p[e - 1] != '>')
        return false;
      std::string dt_s(p + after + 3, e - 1 - (after + 3));
      int dtid = -1;
      if (dt_s == "xs:int" || dt_s == "xs:integer" ||
          dt_s == "xs:positiveInteger" ||
          dt_s == "http://www.w3.org/2001/XMLSchema#int" ||
          dt_s == "http://www.w3.org/2001/XMLSchema#integer")
        dtid = T_INT;
      else if (dt_s == "xs:float" || dt_s == "xs:double" ||
               dt_s == "http://www.w3.org/2001/XMLSchema#float" ||
               dt_s == "http://www.w3.org/2001/XMLSchema#double")
        dtid = T_FLOAT;
      else if (dt_s == "xs:string" ||
               dt_s == "http://www.w3.org/2001/XMLSchema#string")
        dtid = T_STRING;
      else if (dt_s == "xs:boolean" ||
               dt_s == "http://www.w3.org/2001/XMLSchema#boolean")
        dtid = T_BOOL;
      else if (dt_s == "xs:dateTime" || dt_s == "xs:date" ||
               dt_s == "http://www.w3.org/2001/XMLSchema#dateTime")
        dtid = T_DATETIME;
      if (dtid < 0 || dtid != int(pr.value_type)) return false;
    }

    // convert to storage type
    u8 tid = pr.value_type;
    std::string vbytes;
    DT dt{};
    i64 iv = 0; double fv = 0; bool bv = false;
    switch (tid) {
      case T_DEFAULT: case T_STRING:
        vbytes.assign(lit, ln);
        break;
      case T_INT: {
        char* endp = nullptr;
        std::string tmp(lit, ln);
        iv = strtoll(tmp.c_str(), &endp, 10);
        if (!endp || *endp) return false;
        vbytes.append((const char*)&iv, 8);
        break;
      }
      case T_FLOAT: {
        char* endp = nullptr;
        std::string tmp(lit, ln);
        fv = strtod(tmp.c_str(), &endp);
        if (!endp || *endp) return false;
        vbytes.append((const char*)&fv, 8);
        break;
      }
      case T_BOOL: {
        if (ln == 4 && !memcmp(lit, "true", 4)) bv = true;
        else if (ln == 5 && !memcmp(lit, "false", 5)) bv = false;
        else return false;
        vbytes.push_back(bv ? '\x01' : '\x00');
        break;
      }
      case T_DATETIME: {
        if (!parse_dt(lit, ln, &dt)) return false;
        vbytes = dt_isoformat(dt);
        break;
      }
      default:
        return false;  // GEO/BIGFLOAT/VFLOAT etc.
    }

    // posting uid: VALUE_UID for single values, farmhash for list values
    u64 puid = VALUE_UID;
    if (pr.is_list) {
      std::string gb;
      switch (tid) {
        case T_INT: gb.append((const char*)&iv, 8); break;
        case T_FLOAT: gb.append((const char*)&fv, 8); break;
        case T_BOOL: gb.push_back(bv ? '\x01' : '\x00'); break;
        case T_DATETIME: gb = go_time_binary(dt); break;
        default: gb.assign(lit, ln); break;
      }
      puid = farm64((const u8*)gb.data(), gb.size());
    }
    std::string dk = pr.data_prefix;
    put_u64be(dk, subj);
    st->add(std::move(dk), K_VAL, enc_value_posting(puid, tid, vbytes));

    // index tokens
    for (u8 tok : pr.toks) {
      std::vector<std::string> terms;
      switch (tok) {
        case TOK_EXACT: terms.emplace_back(lit, ln); break;
        case TOK_INT: terms.push_back(enc_int_sortable(
            tid == T_INT ? iv : i64(fv))); break;
        case TOK_FLOAT: terms.push_back(enc_int_sortable(
            tid == T_FLOAT ? i64(fv) : iv)); break;
        case TOK_BOOL: terms.emplace_back(1, bv ? '\x01' : '\x00'); break;
        case TOK_YEAR: {
          std::string t; t.push_back(char(dt.y >> 8)); t.push_back(char(dt.y & 0xFF));
          terms.push_back(t); break;
        }
        case TOK_MONTH: {
          std::string t;
          t.push_back(char(dt.y >> 8)); t.push_back(char(dt.y & 0xFF));
          t.push_back(char(dt.mo >> 8)); t.push_back(char(dt.mo & 0xFF));
          terms.push_back(t); break;
        }
        case TOK_DAY: {
          std::string t;
          t.push_back(char(dt.y >> 8)); t.push_back(char(dt.y & 0xFF));
          t.push_back(char(dt.mo >> 8)); t.push_back(char(dt.mo & 0xFF));
          t.push_back(char(dt.d >> 8)); t.push_back(char(dt.d & 0xFF));
          terms.push_back(t); break;
        }
        case TOK_HOUR: {
          std::string t;
          t.push_back(char(dt.y >> 8)); t.push_back(char(dt.y & 0xFF));
          t.push_back(char(dt.mo >> 8)); t.push_back(char(dt.mo & 0xFF));
          t.push_back(char(dt.d >> 8)); t.push_back(char(dt.d & 0xFF));
          t.push_back(char(dt.h >> 8)); t.push_back(char(dt.h & 0xFF));
          terms.push_back(t); break;
        }
        case TOK_TERM: {
          std::set<std::string> uniq;
          for (auto& w : words_ascii(lit, ln)) uniq.insert(w);
          for (auto& w : uniq) terms.push_back(w);
          break;
        }
        case TOK_FULLTEXT: {
          std::set<std::string> uniq;
          for (auto& w : words_ascii(lit, ln))
            if (!is_stopword(w)) uniq.insert(porter_stem(w));
          for (auto& w : uniq) terms.push_back(w);
          break;
        }
        default: break;
      }
      for (auto& t : terms) {
        std::string ik = pr.idx_prefix;
        ik.push_back(char(tok));
        ik += t;
        std::string pay;
        { u64 o = subj; pay.append((const char*)&o, 8); }
        st->add(std::move(ik), K_IDX, std::move(pay));
      }
    }
    ++c->nquads;
    return true;
  }
}

static void map_line(Ctx* c, MapState* st, const char* p, size_t n, u64 ns) {
  while (n && (p[0] == ' ' || p[0] == '\t')) { ++p; --n; }
  while (n && (p[n - 1] == ' ' || p[n - 1] == '\t' || p[n - 1] == '\r')) --n;
  if (!n || p[0] == '#') return;
  if (!try_fast_line(c, st, p, n, ns) && st->slow) {
    fwrite(p, 1, n, st->slow);
    fputc('\n', st->slow);
  }
}

// ---------------------------------------------------------------------------
// SSTable writer (storage/lsm.py _SSTable.write, unencrypted form)
// ---------------------------------------------------------------------------

static u32 crc32_tab[256];
static bool crc32_init_done = false;
static void crc32_init() {
  if (crc32_init_done) return;
  for (u32 i = 0; i < 256; ++i) {
    u32 c = i;
    for (int k = 0; k < 8; ++k) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc32_tab[i] = c;
  }
  crc32_init_done = true;
}
static u32 crc32_of(const u8* p, size_t n) {
  crc32_init();
  u32 c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i) c = crc32_tab[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}
static u32 adler32_of(const u8* p, size_t n) {
  u32 a = 1, b = 0;
  for (size_t i = 0; i < n; ++i) {
    a = (a + p[i]) % 65521;
    b = (b + a) % 65521;
  }
  return (b << 16) | a;
}

// lsm.py _bloom_hashes: crc32|adler32<<32 through two splitmix64 runs
static void bloom_hashes(const std::string& key, u64* h1, u64* h2) {
  const u8* p = (const u8*)key.data();
  u64 x = u64(crc32_of(p, key.size())) | (u64(adler32_of(p, key.size())) << 32);
  u64 z = x + 0x9E3779B97F4A7C15ULL;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  *h1 = z ^ (z >> 31);
  z = x + 0x3C6EF372FE94F82AULL;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  *h2 = (z ^ (z >> 31)) | 1;
}

struct SstWriter {
  FILE* f = nullptr;
  u64 ts = 0, seq = 0, n = 0;
  std::string last_key;
  std::vector<std::pair<std::string, u64>> index;  // every 64th key
  std::vector<u64> h1s, h2s;

  bool open(const char* path) {
    f = fopen(path, "wb");
    if (f) setvbuf(f, nullptr, _IOFBF, 1 << 22);
    return f != nullptr;
  }

  void put(const std::string& key, const std::string& val) {
    if (n % 64 == 0) index.emplace_back(key, u64(ftello(f)));
    if (key != last_key) {
      u64 a, b;
      bloom_hashes(key, &a, &b);
      h1s.push_back(a);
      h2s.push_back(b);
      last_key = key;
    }
    ++seq;
    u32 kl = u32(key.size()), vl = u32(val.size());
    // _ENT = <IQQI>: key_len, ts, seq, val_len
    fwrite(&kl, 4, 1, f);
    fwrite(&ts, 8, 1, f);
    fwrite(&seq, 8, 1, f);
    fwrite(&vl, 4, 1, f);
    fwrite(key.data(), 1, kl, f);
    fwrite(val.data(), 1, vl, f);
    ++n;
  }

  void finish() {
    u64 idx_off = u64(ftello(f));
    for (auto& kv : index) {
      u32 kl = u32(kv.first.size());
      fwrite(&kl, 4, 1, f);
      fwrite(kv.first.data(), 1, kl, f);
      fwrite(&kv.second, 8, 1, f);
    }
    u64 bloom_off = u64(ftello(f));
    size_t nk = std::max<size_t>(1, h1s.size());
    u64 nbits = ((nk * 10 + 7) / 8) * 8;  // _BLOOM_BITS_PER_KEY=10
    std::vector<u8> bits(nbits / 8, 0);
    for (size_t i = 0; i < h1s.size(); ++i)
      for (int k = 0; k < 3; ++k) {  // _BLOOM_HASHES=3
        // Python evaluates (h1 + k*h2) % nbits in arbitrary precision
        // — match it with 128-bit math, NOT 64-bit wraparound
        unsigned __int128 probe =
            (unsigned __int128)h1s[i] + (unsigned __int128)h2s[i] * k;
        u64 b = u64(probe % nbits);
        bits[b >> 3] |= u8(1 << (b & 7));
      }
    fwrite(bits.data(), 1, bits.size(), f);
    // footer: [index_off u64][bloom_off u64][n u64][magic u32]
    u32 magic = 0x4C534D32;
    fwrite(&idx_off, 8, 1, f);
    fwrite(&bloom_off, 8, 1, f);
    fwrite(&n, 8, 1, f);
    fwrite(&magic, 4, 1, f);
    fflush(f);
    fclose(f);
    f = nullptr;
  }
};

// ---------------------------------------------------------------------------
// Reduce phase
// ---------------------------------------------------------------------------

struct RunReader {
  FILE* f = nullptr;
  std::string key, payload;
  u8 kind = 0;
  bool ok = false;

  bool next() {
    char hdr[7];
    if (fread(hdr, 1, 7, f) != 7) { ok = false; return false; }
    u16 kl; u32 pl;
    memcpy(&kl, hdr, 2); kind = u8(hdr[2]); memcpy(&pl, hdr + 3, 4);
    key.resize(kl); payload.resize(pl);
    if (kl && fread(&key[0], 1, kl, f) != kl) { ok = false; return false; }
    if (pl && fread(&payload[0], 1, pl, f) != pl) { ok = false; return false; }
    ok = true;
    return true;
  }
};

struct HeapCmp {
  std::vector<RunReader>* rs;
  bool operator()(int a, int b) const {
    auto& A = (*rs)[a];
    auto& B = (*rs)[b];
    if (A.key != B.key) return A.key > B.key;
    if (A.kind != B.kind) return A.kind > B.kind;
    return A.payload > B.payload;
  }
};

// kind byte of any storage key (0x00 data | 0x02 index | 0x04 reverse),
// -1 for malformed keys
static int key_kind(const std::string& k) {
  if (k.size() < 4 || k[0] != '\x00') return -1;
  u16 alen = (u8(k[1]) << 8) | u8(k[2]);
  if (k.size() < size_t(3 + alen + 1)) return -1;
  return u8(k[3 + alen]);
}

// parse attr + uid + kind back out of a data key (for count flags)
static bool parse_data_key(const std::string& k, std::string* attr, u64* uid) {
  if (k.size() < 12 || k[0] != '\x00') return false;
  u16 alen = (u8(k[1]) << 8) | u8(k[2]);
  if (k.size() < size_t(3 + alen + 1)) return false;
  u8 kind = u8(k[3 + alen]);
  if (kind != 0x00) return false;  // data
  attr->assign(k, 11, alen - 8);
  if (k.size() < size_t(3 + alen + 1 + 8)) return false;
  u64 v = 0;
  for (int i = 0; i < 8; ++i) v = (v << 8) | u8(k[3 + alen + 1 + i]);
  *uid = v;
  return true;
}

}  // namespace

// ---------------------------------------------------------------------------
// C API
// ---------------------------------------------------------------------------

extern "C" {

void* bulk_new() { return new Ctx(); }
void bulk_free(void* h) { delete (Ctx*)h; }

// scan for xid names (same over-approximation as bulk2._XID_RE):
// every <...> payload + every _:token. Returns distinct-name count.
i64 bulk_scan_xids(void* h, const char* text, i64 n) {
  Ctx* c = (Ctx*)h;
  std::set<std::string> names;
  for (i64 i = 0; i < n; ++i) {
    if (text[i] == '<') {
      i64 j = i + 1;
      while (j < n && text[j] != '>' && text[j] != '\n') ++j;
      if (j < n && text[j] == '>') {
        std::string ref(text + i + 1, j - i - 1);
        bool isuid = ref.size() > 2 && ref[0] == '0' && ref[1] == 'x';
        bool digits = !ref.empty();
        for (char ch : ref) if (ch < '0' || ch > '9') { digits = false; break; }
        if (!isuid && !digits) names.insert(std::move(ref));
        i = j;
      }
    } else if (text[i] == '_' && i + 1 < n && text[i + 1] == ':') {
      i64 j = i + 2;
      while (j < n) {
        char ch = text[j];
        bool wc = (ch >= 'a' && ch <= 'z') || (ch >= 'A' && ch <= 'Z') ||
                  (ch >= '0' && ch <= '9') || ch == '_' || ch == '.' || ch == '-';
        if (!wc) break;
        ++j;
      }
      if (j > i + 2) names.insert(std::string(text + i, j - i));
      i = j - 1;
    }
  }
  c->xid_order.assign(names.begin(), names.end());
  return i64(c->xid_order.size());
}

void bulk_set_base(void* h, u64 base) {
  Ctx* c = (Ctx*)h;
  c->base = base;
  c->xids.clear();
  c->xids.reserve(c->xid_order.size() * 2);
  for (size_t i = 0; i < c->xid_order.size(); ++i)
    c->xids[c->xid_order[i]] = base + i;
}

u64 bulk_xid_lookup(void* h, const char* name, i64 n) {
  Ctx* c = (Ctx*)h;
  auto it = c->xids.find(std::string(name, n));
  return it == c->xids.end() ? 0 : it->second;
}

void bulk_clear_preds(void* h) { ((Ctx*)h)->preds.clear(); }

// flags: 1 list | 2 reverse | 4 count | 8 lang
// toks: tokenizer identifier bytes (only ids the C++ side supports)
int bulk_add_pred(void* h, const char* name, i64 nlen, int value_type,
                  int flags, const u8* toks, i64 ntoks, u64 ns) {
  Ctx* c = (Ctx*)h;
  Pred p;
  p.value_type = u8(value_type);
  p.is_list = flags & 1;
  p.reverse = flags & 2;
  p.count = flags & 4;
  p.has_lang = flags & 8;
  p.toks.assign(toks, toks + ntoks);
  std::string attr(name, nlen);
  p.data_prefix = key_head(ns, attr, 0x00);
  p.rev_prefix = key_head(ns, attr, 0x04);
  p.idx_prefix = key_head(ns, attr, 0x02);
  c->preds[attr] = std::move(p);
  return 0;
}

// map `text` into sorted spill runs under workdir; unhandled lines are
// appended to slow_path. Returns nquads mapped natively, or -1.
i64 bulk_map(void* h, const char* text, i64 n, u64 ns,
             const char* workdir, const char* slow_path, i64 spill_entries) {
  Ctx* c = (Ctx*)h;
  MapState st;
  st.ctx = c;
  st.workdir = workdir;
  st.spill_at = size_t(spill_entries);
  st.slow = fopen(slow_path, "wb");
  if (!st.slow) return -1;
  u64 before = c->nquads;
  i64 i = 0;
  while (i < n) {
    i64 j = i;
    while (j < n && text[j] != '\n') ++j;
    map_line(c, &st, text + i, j - i, ns);
    i = j + 1;
  }
  st.spill();
  fclose(st.slow);
  if (!c->err.empty()) return -1;
  return i64(c->nquads - before);
}

i64 bulk_run_count(void* h) { return i64(((Ctx*)h)->runs.size()); }
i64 bulk_run_path(void* h, i64 i, char* out, i64 cap) {
  Ctx* c = (Ctx*)h;
  if (i < 0 || size_t(i) >= c->runs.size()) return -1;
  i64 l = i64(c->runs[i].size());
  if (l >= cap) return -1;
  memcpy(out, c->runs[i].c_str(), l + 1);
  return l;
}

// merge `paths` (newline-joined run files, native + python-produced) and
// emit the final record stream: [u16 klen][key][u32 rlen][record] into
// out_main; CountKey records into out_counts. Returns record count, -1
// on error.
// sst=0: out_main is a [u16 klen][key][u32 rlen][rec] stream.
// sst=1: out_main is a finished SSTable (storage/lsm.py _SSTable
//        layout, unencrypted) with version `ts` and seqs from seq_base+1.
// out_stats (may be null/empty): index-key selectivity records
// [u16 klen][key][u64 uid_count], one per index key — the StatsHolder
// feed the Python slow path emits inline but the native path previously
// skipped (NOTES_NEXT_ROUND §2 known gap).
i64 bulk_reduce(void* h, const char* paths_joined, i64 plen,
                u64 max_part_uids, const char* out_main,
                const char* out_counts, const char* out_stats, u64 ns,
                i64 sst, u64 ts, u64 seq_base) {
  Ctx* c = (Ctx*)h;
  std::vector<std::string> paths;
  {
    std::string all(paths_joined, plen);
    size_t pos = 0;
    while (pos < all.size()) {
      size_t nl = all.find('\n', pos);
      if (nl == std::string::npos) nl = all.size();
      if (nl > pos) paths.emplace_back(all, pos, nl - pos);
      pos = nl + 1;
    }
  }
  std::vector<RunReader> rs(paths.size());
  std::priority_queue<int, std::vector<int>, HeapCmp> heap{HeapCmp{&rs}};
  for (size_t i = 0; i < paths.size(); ++i) {
    rs[i].f = fopen(paths[i].c_str(), "rb");
    if (!rs[i].f) return -1;
    setvbuf(rs[i].f, nullptr, _IOFBF, 1 << 20);
    if (rs[i].next()) heap.push(int(i));
  }
  FILE* fm = nullptr;
  SstWriter sw;
  if (sst) {
    sw.ts = ts;
    sw.seq = seq_base;
    if (!sw.open(out_main)) return -1;
  } else {
    fm = fopen(out_main, "wb");
    if (!fm) return -1;
    setvbuf(fm, nullptr, _IOFBF, 1 << 22);
  }
  FILE* fs = nullptr;
  if (out_stats && out_stats[0]) {
    // stats are advisory (the Python reader tolerates a missing file):
    // an open failure must not fail the reduce itself
    fs = fopen(out_stats, "wb");
    if (fs) setvbuf(fs, nullptr, _IOFBF, 1 << 20);
  }

  // (attr, count) -> uids, for @count predicates
  std::map<std::pair<std::string, u64>, std::vector<u64>> counts;
  // split-part records live in the 0x03 key region, AFTER every data
  // key — they go into the second (sorted) batch, keeping the main
  // stream in ascending key order for ingest_sorted
  std::vector<std::pair<std::string, std::string>> extra;

  i64 nrecords = 0;
  std::string cur_key;
  std::vector<u64> uids;
  std::map<u64, std::string> posts;  // posting uid -> wire bytes (last wins)
  bool have = false;

  auto emit_group = [&]() {
    if (!have) return;
    std::sort(uids.begin(), uids.end());
    uids.erase(std::unique(uids.begin(), uids.end()), uids.end());

    std::string attr;
    u64 subj = 0;
    bool is_data = parse_data_key(cur_key, &attr, &subj);
    if (is_data && !uids.empty()) {
      auto pit = c->preds.find(attr);
      if (pit != c->preds.end() && pit->second.count)
        counts[{attr, u64(uids.size())}].push_back(subj);
    }
    if (fs && !uids.empty() && cur_key.size() <= 0xFFFF &&
        key_kind(cur_key) == 0x02) {
      // index key: emit its (key, posting-count) selectivity record;
      // oversized keys are skipped — a truncated u16 klen would corrupt
      // every later record in the stream
      u16 kl = u16(cur_key.size());
      u64 n = u64(uids.size());
      fwrite(&kl, 2, 1, fs);
      fwrite(cur_key.data(), 1, kl, fs);
      fwrite(&n, 8, 1, fs);
    }

    auto write_rec = [&](const std::string& key, const std::string& rec) {
      if (sst) {
        sw.put(key, rec);
      } else {
        u16 kl = u16(key.size());
        u32 rl = u32(rec.size());
        fwrite(&kl, 2, 1, fm);
        fwrite(key.data(), 1, kl, fm);
        fwrite(&rl, 4, 1, fm);
        fwrite(rec.data(), 1, rl, fm);
      }
      ++nrecords;
    };

    std::vector<const std::string*> ordered;
    for (auto& kv : posts) ordered.push_back(&kv.second);

    if (!posts.empty() || uids.size() <= max_part_uids) {
      std::string pack, rec;
      serialize_uids(uids, pack);
      encode_rollup(pack, ordered, {}, rec);
      write_rec(cur_key, rec);
    } else {
      // multi-part split (posting/pl.py rollup_writes)
      u64 per = max_part_uids / 2;
      if (per < 1) per = 1;
      std::vector<u64> starts;
      for (size_t i = 0; i < uids.size(); i += per) {
        size_t cnt = std::min(size_t(per), uids.size() - i);
        std::vector<u64> chunk(uids.begin() + i, uids.begin() + i + cnt);
        starts.push_back(chunk[0]);
        std::string pack, rec;
        serialize_uids(chunk, pack);
        encode_rollup(pack, {}, {}, rec);
        extra.emplace_back(split_key(cur_key, chunk[0]), std::move(rec));
      }
      std::string pack, rec;
      serialize_uids({}, pack);
      encode_rollup(pack, {}, starts, rec);
      write_rec(cur_key, rec);
    }
    uids.clear();
    posts.clear();
  };

  while (!heap.empty()) {
    int i = heap.top();
    heap.pop();
    RunReader& r = rs[i];
    if (!have || r.key != cur_key) {
      emit_group();
      cur_key = r.key;
      have = true;
    }
    if (r.kind == K_VAL) {
      if (r.payload.size() >= 9) {
        u64 puid;
        memcpy(&puid, r.payload.data() + 1, 8);
        posts[puid] = r.payload;
      }
    } else if (r.payload.size() == 8) {
      u64 u;
      memcpy(&u, r.payload.data(), 8);
      uids.push_back(u);
    }
    if (r.next()) heap.push(i);
  }
  emit_group();
  if (sst) sw.finish();
  else fclose(fm);
  if (fs) fclose(fs);
  for (auto& r : rs) if (r.f) fclose(r.f);

  FILE* fc = fopen(out_counts, "wb");
  if (!fc) return -1;
  std::vector<std::pair<std::string, std::string>> crecs;
  for (auto& kv : counts) {
    // CountKey: head + [count u32 BE][rev u8]
    std::string key = key_head(ns, kv.first.first, 0x08);
    u32 cnt = u32(kv.first.second);
    key.push_back(char((cnt >> 24) & 0xFF)); key.push_back(char((cnt >> 16) & 0xFF));
    key.push_back(char((cnt >> 8) & 0xFF)); key.push_back(char(cnt & 0xFF));
    key.push_back('\x00');
    std::vector<u64> us = kv.second;
    std::sort(us.begin(), us.end());
    us.erase(std::unique(us.begin(), us.end()), us.end());
    std::string pack, rec;
    serialize_uids(us, pack);
    encode_rollup(pack, {}, {}, rec);
    crecs.emplace_back(std::move(key), std::move(rec));
  }
  for (auto& kr : extra) crecs.emplace_back(std::move(kr));
  // byte order, not (attr,count) order: ingest_sorted needs key order
  std::sort(crecs.begin(), crecs.end());
  for (auto& kr : crecs) {
    u16 kl = u16(kr.first.size());
    u32 rl = u32(kr.second.size());
    fwrite(&kl, 2, 1, fc);
    fwrite(kr.first.data(), 1, kl, fc);
    fwrite(&rl, 4, 1, fc);
    fwrite(kr.second.data(), 1, rl, fc);
  }
  fclose(fc);
  return nrecords;
}

}  // extern "C"

"""Scalar value types & conversion.

Mirrors /root/reference/types/ (scalar_types.go TypeID enum, conversion.go
Convert, sort.go/compare.go ordering semantics). Values are stored in the
posting layer as (type_id, payload-bytes) and converted on read; binary
payload encodings follow the reference's conventions (little-endian int64 /
float64, RFC3339 time strings parsed to datetime, geo as WKB-lite GeoJSON).
"""

from __future__ import annotations

import datetime as _dt
import json
import re
import struct
from dataclasses import dataclass
from enum import IntEnum
from typing import Any, Optional


class TypeID(IntEnum):
    # ids match pb.Posting.ValType semantics (ref protos/pb.proto:310)
    DEFAULT = 0
    BINARY = 1
    INT = 2
    FLOAT = 3
    BOOL = 4
    DATETIME = 5
    GEO = 6
    UID = 7
    PASSWORD = 8
    STRING = 9
    OBJECT = 10
    BIGFLOAT = 11
    VFLOAT = 12  # float32 vector (ref types/scalar_types.go VFloatID)


_NAMES = {
    "default": TypeID.DEFAULT,
    "binary": TypeID.BINARY,
    "int": TypeID.INT,
    "float": TypeID.FLOAT,
    "bool": TypeID.BOOL,
    "datetime": TypeID.DATETIME,
    "geo": TypeID.GEO,
    "uid": TypeID.UID,
    "password": TypeID.PASSWORD,
    "string": TypeID.STRING,
    "bigfloat": TypeID.BIGFLOAT,
    "float32vector": TypeID.VFLOAT,
}
_ID2NAME = {v: k for k, v in _NAMES.items()}


def type_from_name(name: str) -> TypeID:
    # case-insensitive: the reference schema spells both dateTime/datetime
    try:
        return _NAMES[name.lower()]
    except KeyError:
        raise ValueError(f"unknown type name {name!r}") from None


def type_name(tid: TypeID) -> str:
    return _ID2NAME.get(tid, "default")


@dataclass
class Val:
    """A typed value (ref types/value.go Val)."""

    tid: TypeID
    value: Any

    def __repr__(self):
        return f"Val({type_name(self.tid)}, {self.value!r})"


# ---------------------------------------------------------------------------
# Binary encode/decode (posting payloads).
# ---------------------------------------------------------------------------


def to_binary(v: Val) -> bytes:
    t = v.tid
    if t in (TypeID.DEFAULT, TypeID.STRING, TypeID.PASSWORD):
        return str(v.value).encode("utf-8")
    if t == TypeID.BINARY:
        return bytes(v.value)
    if t == TypeID.INT:
        return struct.pack("<q", int(v.value))
    if t == TypeID.FLOAT:
        return struct.pack("<d", float(v.value))
    if t == TypeID.BOOL:
        return b"\x01" if v.value else b"\x00"
    if t == TypeID.DATETIME:
        dt = v.value
        if isinstance(dt, str):
            dt = parse_datetime(dt)
        return dt.isoformat().encode("utf-8")
    if t == TypeID.GEO:
        return json.dumps(v.value, separators=(",", ":")).encode("utf-8")
    if t == TypeID.BIGFLOAT:
        return str(v.value).encode("utf-8")
    if t == TypeID.VFLOAT:
        import numpy as np

        return np.asarray(v.value, dtype=np.float32).tobytes()
    raise ValueError(f"cannot binary-encode {t}")


def from_binary(tid: TypeID, data: bytes) -> Val:
    if tid in (TypeID.DEFAULT, TypeID.STRING, TypeID.PASSWORD):
        return Val(tid, data.decode("utf-8"))
    if tid == TypeID.BINARY:
        return Val(tid, data)
    if tid == TypeID.INT:
        return Val(tid, struct.unpack("<q", data)[0])
    if tid == TypeID.FLOAT:
        return Val(tid, struct.unpack("<d", data)[0])
    if tid == TypeID.BOOL:
        return Val(tid, data == b"\x01")
    if tid == TypeID.DATETIME:
        return Val(tid, parse_datetime(data.decode("utf-8")))
    if tid == TypeID.GEO:
        return Val(tid, json.loads(data.decode("utf-8")))
    if tid == TypeID.BIGFLOAT:
        from decimal import Decimal

        return Val(tid, Decimal(data.decode("utf-8")))
    if tid == TypeID.VFLOAT:
        import numpy as np

        return Val(tid, np.frombuffer(data, dtype=np.float32).copy())
    raise ValueError(f"cannot binary-decode {tid}")


# ---------------------------------------------------------------------------
# Conversion (ref types/conversion.go Convert).
# ---------------------------------------------------------------------------


_FRAC_RE = re.compile(r"(?<=\d)\.(\d+)")


def _norm_frac(x: str) -> str:
    """Normalize fractional seconds to exactly 6 digits: RFC3339 allows
    any precision ('.52Z'), but fromisoformat before Python 3.11 only
    accepts 3 or 6 digits. Extra precision truncates (Go parses
    nanoseconds; microseconds is the most a datetime can hold)."""
    return _FRAC_RE.sub(
        lambda m: "." + m.group(1)[:6].ljust(6, "0"), x, count=1
    )


def parse_datetime(s: str) -> _dt.datetime:
    s = s.strip()
    # RFC3339 with optional fractional seconds / zone; also bare dates.
    for parse in (
        lambda x: _dt.datetime.fromisoformat(
            _norm_frac(x.replace("Z", "+00:00"))
        ),
        lambda x: _dt.datetime.strptime(x, "%Y-%m-%d"),
        lambda x: _dt.datetime.strptime(x, "%Y-%m"),
        lambda x: _dt.datetime.strptime(x, "%Y"),
    ):
        try:
            return parse(s)
        except ValueError:
            continue
    raise ValueError(f"cannot parse datetime {s!r}")


def convert(v: Val, to: TypeID) -> Val:
    """Convert v to target type (subset of ref types/conversion.go)."""
    if v.tid == to:
        return v
    x = v.value
    src = v.tid
    try:
        if to == TypeID.STRING or to == TypeID.DEFAULT:
            if src == TypeID.DATETIME:
                return Val(to, x.isoformat())
            if src == TypeID.BOOL:
                return Val(to, "true" if x else "false")
            return Val(to, str(x))
        if to == TypeID.INT:
            if src in (TypeID.STRING, TypeID.DEFAULT):
                return Val(to, int(float(x)) if "." in str(x) else int(x))
            if src == TypeID.FLOAT:
                return Val(to, int(x))
            if src == TypeID.BOOL:
                return Val(to, 1 if x else 0)
            if src == TypeID.DATETIME:
                return Val(to, int(x.timestamp()))
        if to == TypeID.FLOAT:
            if src in (TypeID.STRING, TypeID.DEFAULT):
                return Val(to, float(x))
            if src == TypeID.INT:
                return Val(to, float(x))
            if src == TypeID.BOOL:
                return Val(to, 1.0 if x else 0.0)
            if src == TypeID.DATETIME:
                return Val(to, x.timestamp())
        if to == TypeID.BOOL:
            if src in (TypeID.STRING, TypeID.DEFAULT):
                if str(x).lower() in ("true", "1"):
                    return Val(to, True)
                if str(x).lower() in ("false", "0"):
                    return Val(to, False)
                raise ValueError(x)
            if src == TypeID.INT:
                return Val(to, x != 0)
            if src == TypeID.FLOAT:
                return Val(to, x != 0.0)
        if to == TypeID.DATETIME:
            if src in (TypeID.STRING, TypeID.DEFAULT):
                return Val(to, parse_datetime(str(x)))
            if src == TypeID.INT:
                return Val(to, _dt.datetime.fromtimestamp(x, _dt.timezone.utc))
        if to == TypeID.VFLOAT:
            import numpy as np

            if src in (TypeID.STRING, TypeID.DEFAULT):
                return Val(to, np.asarray(json.loads(str(x)), dtype=np.float32))
            if src == TypeID.BINARY:
                return Val(to, np.frombuffer(x, dtype=np.float32).copy())
        if to == TypeID.GEO and src in (TypeID.STRING, TypeID.DEFAULT):
            # single quotes tolerated like ref types/conversion.go:213
            return Val(to, json.loads(str(x).replace("'", '"')))
        if to == TypeID.PASSWORD and src in (TypeID.STRING, TypeID.DEFAULT):
            # plaintext is hashed at ingest (ref types/conversion.go:220
            # StringID->PasswordID bcrypt): stored form = hex(salt||PBKDF2)
            import hashlib as _hl
            import os as _os

            salt = _os.urandom(16)
            digest = _hl.pbkdf2_hmac("sha256", str(x).encode(), salt, 10_000)
            return Val(to, (salt + digest).hex())
        if to == TypeID.BINARY:
            return Val(to, to_binary(v))
    except (ValueError, TypeError) as e:
        raise ValueError(f"cannot convert {v!r} to {type_name(to)}: {e}") from None
    raise ValueError(f"cannot convert {type_name(src)} to {type_name(to)}")


def _sort_key(v: Val):
    if v.tid == TypeID.DATETIME:
        x = v.value
        if x.tzinfo is None:
            x = x.replace(tzinfo=_dt.timezone.utc)
        return x
    return v.value


def compare_vals(a: Val, b: Val) -> int:
    """Three-way compare for same-type Vals (ref types/compare.go)."""
    ka, kb = _sort_key(a), _sort_key(b)
    if ka < kb:
        return -1
    if ka > kb:
        return 1
    return 0

from dgraph_tpu.types.types import TypeID, Val, convert, compare_vals

"""Bundled gRPC client: the pydgraph surface over the api.Dgraph wire.

Mirrors pydgraph's DgraphClientStub/DgraphClient/Txn trio (the dgo
contract, ref protos/pb.proto service Dgraph): works against this
framework's gRPC server AND any server speaking the same protocol.

    stub = DgraphClientStub("localhost:9080")
    client = DgraphClient(stub)
    client.alter(schema="name: string @index(exact) .")
    txn = client.txn()
    txn.mutate(set_nquads='_:a <name> "Alice" .')
    txn.commit()
    print(client.txn(read_only=True).query('{ q(func: has(name)) { name } }'))
"""

from __future__ import annotations

import json
from typing import Dict, Optional

import grpc

from dgraph_tpu.protos import load_api_pb2

pb = load_api_pb2()


class DgraphClientStub:
    def __init__(self, addr: str = "localhost:9080", credentials=None):
        self.addr = addr
        self.channel = (
            grpc.secure_channel(addr, credentials)
            if credentials is not None
            else grpc.insecure_channel(addr)
        )
        u = self.channel.unary_unary
        self.login = u(
            "/api.Dgraph/Login",
            request_serializer=pb.LoginRequest.SerializeToString,
            response_deserializer=pb.Response.FromString,
        )
        self.query = u(
            "/api.Dgraph/Query",
            request_serializer=pb.Request.SerializeToString,
            response_deserializer=pb.Response.FromString,
        )
        self.alter = u(
            "/api.Dgraph/Alter",
            request_serializer=pb.Operation.SerializeToString,
            response_deserializer=pb.Payload.FromString,
        )
        self.commit_or_abort = u(
            "/api.Dgraph/CommitOrAbort",
            request_serializer=pb.TxnContext.SerializeToString,
            response_deserializer=pb.TxnContext.FromString,
        )
        self.check_version = u(
            "/api.Dgraph/CheckVersion",
            request_serializer=pb.Check.SerializeToString,
            response_deserializer=pb.Version.FromString,
        )

    def close(self):
        self.channel.close()


class Txn:
    """A transaction bound to one client stub (pydgraph Txn surface)."""

    def __init__(self, client: "DgraphClient", read_only: bool = False):
        self._client = client
        self._read_only = read_only
        self._start_ts = 0
        self._finished = False

    def query(
        self, q: str, variables: Optional[Dict[str, str]] = None
    ) -> dict:
        req = pb.Request(
            query=q,
            start_ts=self._start_ts,
            read_only=self._read_only,
        )
        for k, v in (variables or {}).items():
            req.vars[k] = v
        resp = self._client._stub.query(req)
        if resp.txn.start_ts:
            self._start_ts = resp.txn.start_ts
        return json.loads(resp.json or b"{}")

    def mutate(
        self,
        set_nquads: str = "",
        del_nquads: str = "",
        set_obj=None,
        del_obj=None,
        cond: Optional[str] = None,
        commit_now: bool = False,
    ) -> dict:
        if self._read_only:
            raise RuntimeError("read-only transactions cannot mutate")
        req = pb.Request(start_ts=self._start_ts, commit_now=commit_now)
        m = req.mutations.add()
        if set_nquads:
            m.set_nquads = set_nquads.encode()
        if del_nquads:
            m.del_nquads = del_nquads.encode()
        if set_obj is not None:
            m.set_json = json.dumps(set_obj).encode()
        if del_obj is not None:
            m.delete_json = json.dumps(del_obj).encode()
        if cond:
            m.cond = cond
        resp = self._client._stub.query(req)
        if resp.txn.start_ts:
            self._start_ts = resp.txn.start_ts
        if commit_now:
            self._finished = True
        return dict(resp.uids)

    def do_request(self, query: str, mutations, commit_now: bool = True):
        """Upsert block: query + conditional mutations (pydgraph
        txn.do_request shape). mutations: [(set_nquads, cond)]"""
        req = pb.Request(
            start_ts=self._start_ts, query=query, commit_now=commit_now
        )
        for set_nq, cond in mutations:
            m = req.mutations.add()
            m.set_nquads = set_nq.encode()
            if cond:
                m.cond = cond
        resp = self._client._stub.query(req)
        if commit_now:
            self._finished = True
        return dict(resp.uids)

    def commit(self) -> int:
        if self._finished:
            raise RuntimeError("transaction already finished")
        self._finished = True
        if not self._start_ts:
            return 0  # nothing happened
        ctx = self._client._stub.commit_or_abort(
            pb.TxnContext(start_ts=self._start_ts)
        )
        return ctx.commit_ts

    def discard(self):
        if self._finished or not self._start_ts:
            self._finished = True
            return
        self._finished = True
        self._client._stub.commit_or_abort(
            pb.TxnContext(start_ts=self._start_ts, aborted=True)
        )


class DgraphClient:
    def __init__(self, *stubs: DgraphClientStub):
        if not stubs:
            raise ValueError("at least one stub required")
        self._stubs = list(stubs)
        self._i = 0

    @property
    def _stub(self) -> DgraphClientStub:
        # round-robin across stubs (pydgraph any_client)
        self._i = (self._i + 1) % len(self._stubs)
        return self._stubs[self._i]

    def login(self, userid: str, password: str, namespace: int = 0) -> dict:
        resp = self._stub.login(
            pb.LoginRequest(
                userid=userid, password=password, namespace=namespace
            )
        )
        return json.loads(resp.json or b"{}")

    def alter(
        self,
        schema: str = "",
        drop_attr: str = "",
        drop_all: bool = False,
    ):
        op = pb.Operation(schema=schema, drop_attr=drop_attr, drop_all=drop_all)
        return self._stub.alter(op)

    def txn(self, read_only: bool = False) -> Txn:
        return Txn(self, read_only=read_only)

    def check_version(self) -> str:
        return self._stub.check_version(pb.Check()).tag

from dgraph_tpu.enc.enc import encrypt_stream, decrypt_stream, read_key_file

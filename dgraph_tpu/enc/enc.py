"""Encryption-at-rest helpers: AES-CTR streams for backups/exports.

Mirrors /root/reference/enc/util.go (GetReaderWriter: AES-CTR with a
random IV prepended to the stream) and the key-file plumbing of
x/acl_enc_keys.go. Key sizes 16/24/32 select AES-128/192/256.
"""

from __future__ import annotations

import os
from typing import Optional

from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes

IV_SIZE = 16


def read_key_file(path: str) -> bytes:
    with open(path, "rb") as f:
        key = f.read().strip()
    if len(key) not in (16, 24, 32):
        raise ValueError(
            f"encryption key must be 16/24/32 bytes, got {len(key)}"
        )
    return key


def encrypt_stream(data: bytes, key: bytes) -> bytes:
    """IV || AES-CTR(data) (ref enc/util.go:20 GetWriter)."""
    iv = os.urandom(IV_SIZE)
    enc = Cipher(algorithms.AES(key), modes.CTR(iv)).encryptor()
    return iv + enc.update(data) + enc.finalize()


def decrypt_stream(data: bytes, key: bytes) -> bytes:
    iv, body = data[:IV_SIZE], data[IV_SIZE:]
    dec = Cipher(algorithms.AES(key), modes.CTR(iv)).decryptor()
    return dec.update(body) + dec.finalize()
